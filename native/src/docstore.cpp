// lodstore — embedded WAL-backed document store + CSV ingest engine.
//
// Native system-of-record for learningorchestra_tpu, playing the role
// MongoDB (a C++ server) plays in the reference deployment
// (reference: docker-compose.yml:42-90): every artifact is a collection
// of JSON documents whose _id=0 document is the metadata record.
//
// On-disk format is IDENTICAL to the pure-Python DocumentStore
// (learningorchestra_tpu/store/document_store.py): one JSONL write-ahead
// log per collection, each line one of
//   {"op":"i","d":{...,"_id":N}}     insert
//   {"op":"u","id":N,"d":{...}}      top-level field merge
//   {"op":"d","id":N}                delete
//   {"op":"n","v":N}                 next-id watermark (compaction)
// so the two backends are interchangeable on the same directory.
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image).
// All returned buffers are malloc'd and must be released with lods_free.

#include <algorithm>
#include <cctype>
#include <charconv>
#include <cmath>
#include <cerrno>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <limits>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

thread_local std::string g_error;

void set_error(const std::string &msg) { g_error = msg; }

// ---------------------------------------------------------------------------
// Minimal JSON span scanner: enough to find top-level keys/values of an
// object, merge two objects at the top level, and validate value spans.
// Documents are stored as raw JSON text; we never build a DOM.
// ---------------------------------------------------------------------------

size_t skip_ws(const char *s, size_t i, size_t n) {
  while (i < n && (s[i] == ' ' || s[i] == '\t' || s[i] == '\n' || s[i] == '\r'))
    i++;
  return i;
}

// Returns index one past the end of the JSON value starting at i, or
// std::string::npos on malformed input.
size_t skip_value(const char *s, size_t i, size_t n) {
  i = skip_ws(s, i, n);
  if (i >= n) return std::string::npos;
  char c = s[i];
  if (c == '"') {
    i++;
    while (i < n) {
      if (s[i] == '\\') {
        i += 2;
      } else if (s[i] == '"') {
        return i + 1;
      } else {
        i++;
      }
    }
    return std::string::npos;
  }
  if (c == '{' || c == '[') {
    char open = c, close = (c == '{') ? '}' : ']';
    int depth = 0;
    while (i < n) {
      if (s[i] == '"') {
        size_t end = skip_value(s, i, n);
        if (end == std::string::npos) return std::string::npos;
        i = end;
        continue;
      }
      if (s[i] == open) depth++;
      if (s[i] == close) {
        depth--;
        if (depth == 0) return i + 1;
      }
      i++;
    }
    return std::string::npos;
  }
  // number / true / false / null
  size_t start = i;
  while (i < n && s[i] != ',' && s[i] != '}' && s[i] != ']' && s[i] != ' ' &&
         s[i] != '\t' && s[i] != '\n' && s[i] != '\r')
    i++;
  return (i > start) ? i : std::string::npos;
}

struct KV {
  std::string key;      // decoded enough for comparison (raw inner text)
  std::string raw_val;  // raw JSON value text
};

// Parse the top-level pairs of a JSON object into (key, raw value) pairs.
// Keys are returned as their raw string contents (escapes left intact —
// both sides of any comparison come through this same function).
bool parse_object(const std::string &text, std::vector<KV> &out) {
  const char *s = text.data();
  size_t n = text.size();
  size_t i = skip_ws(s, 0, n);
  if (i >= n || s[i] != '{') return false;
  i = skip_ws(s, i + 1, n);
  if (i < n && s[i] == '}') return true;  // empty object
  while (i < n) {
    if (s[i] != '"') return false;
    size_t key_end = skip_value(s, i, n);
    if (key_end == std::string::npos) return false;
    std::string key = text.substr(i + 1, key_end - i - 2);
    i = skip_ws(s, key_end, n);
    if (i >= n || s[i] != ':') return false;
    i = skip_ws(s, i + 1, n);
    size_t val_end = skip_value(s, i, n);
    if (val_end == std::string::npos) return false;
    out.push_back({std::move(key), text.substr(i, val_end - i)});
    i = skip_ws(s, val_end, n);
    if (i < n && s[i] == ',') {
      i = skip_ws(s, i + 1, n);
      continue;
    }
    if (i < n && s[i] == '}') return true;
    return false;
  }
  return false;
}

std::string build_object(const std::vector<KV> &pairs) {
  std::string out = "{";
  for (size_t i = 0; i < pairs.size(); i++) {
    if (i) out += ",";
    out += '"';
    out += pairs[i].key;
    out += "\":";
    out += pairs[i].raw_val;
  }
  out += "}";
  return out;
}

// doc.update(fields) at the top level, Python-dict style; "_id" in fields
// is ignored (the store owns identity).
std::string merge_objects(const std::string &base, const std::string &fields) {
  std::vector<KV> b, f;
  if (!parse_object(base, b)) return base;
  if (!parse_object(fields, f)) return base;
  for (auto &kv : f) {
    if (kv.key == "_id") continue;
    bool replaced = false;
    for (auto &existing : b) {
      if (existing.key == kv.key) {
        existing.raw_val = kv.raw_val;
        replaced = true;
        break;
      }
    }
    if (!replaced) b.push_back(kv);
  }
  return build_object(b);
}

// Find a top-level field's raw value; returns false if absent.
bool get_field(const std::string &doc, const char *field, std::string &out) {
  std::vector<KV> pairs;
  if (!parse_object(doc, pairs)) return false;
  for (auto &kv : pairs) {
    if (kv.key == field) {
      out = kv.raw_val;
      return true;
    }
  }
  return false;
}

// Inject "_id":N into a doc that does not carry one (replace if present).
std::string with_id(const std::string &doc, long long id) {
  std::vector<KV> pairs;
  char idbuf[32];
  snprintf(idbuf, sizeof idbuf, "%lld", id);
  if (!parse_object(doc, pairs)) return doc;
  for (auto &kv : pairs) {
    if (kv.key == "_id") {
      kv.raw_val = idbuf;
      return build_object(pairs);
    }
  }
  pairs.push_back({"_id", idbuf});
  return build_object(pairs);
}

// ---------------------------------------------------------------------------
// Collection + store
// ---------------------------------------------------------------------------

bool valid_name(const std::string &name) {
  if (name.empty()) return false;
  auto word = [](char c) {
    return std::isalnum(static_cast<unsigned char>(c)) || c == '_';
  };
  if (!word(name[0])) return false;
  for (char c : name)
    if (!word(c) && c != '.' && c != '-') return false;
  return true;
}

struct Collection {
  std::string path;
  bool durable;
  std::mutex mu;
  std::map<long long, std::string> docs;  // id -> raw JSON doc (with _id)
  long long next_id = 0;
  FILE *fh = nullptr;

  ~Collection() {
    if (fh) fclose(fh);
  }

  bool replay() {
    FILE *in = fopen(path.c_str(), "r");
    if (!in) return true;  // nothing to replay
    long long max_seen = -1;
    std::string line;
    char buf[1 << 16];
    std::string pending;
    // Torn-tail recovery (same contract as the Python backend): a
    // crash mid-append leaves at most one partial record at the END.
    // Replay applies records up to the first invalid one, then (a) if
    // any VALID record follows the damage, refuses to open — that is
    // mid-file corruption, not a crash artifact; (b) otherwise
    // truncates to the last good record so the next append starts a
    // clean line instead of gluing onto partial bytes.
    long good_end = 0;
    bool torn = false, damaged = false;
    while (fgets(buf, sizeof buf, in)) {
      pending += buf;
      if (pending.empty() || pending.back() != '\n') continue;  // long line
      line.swap(pending);
      pending.clear();
      long line_end = ftell(in);
      while (!line.empty() && (line.back() == '\n' || line.back() == '\r'))
        line.pop_back();
      if (line.empty()) {
        // Inside a torn region a blank line must NOT advance good_end
        // — truncation would then keep the garbage bytes before it,
        // and the next append would glue onto them.
        if (!torn) good_end = line_end;
        continue;
      }
      std::vector<KV> op;
      if (!parse_object(line, op)) {
        if (torn) {
          continue;  // still scanning the damaged region
        }
        torn = true;
        continue;
      }
      std::string kind, d, idv, v;
      for (auto &kv : op) {
        if (kv.key == "op") kind = kv.raw_val;
        else if (kv.key == "d") d = kv.raw_val;
        else if (kv.key == "id") idv = kv.raw_val;
        else if (kv.key == "v") v = kv.raw_val;
      }
      if (torn) {
        // A parseable record AFTER invalid bytes: mid-file damage.
        if (!kind.empty()) { damaged = true; break; }
        continue;
      }
      if (kind == "\"i\"") {
        std::string idraw;
        if (!get_field(d, "_id", idraw)) continue;
        long long id = strtoll(idraw.c_str(), nullptr, 10);
        docs[id] = d;
        if (id > max_seen) max_seen = id;
      } else if (kind == "\"u\"") {
        long long id = strtoll(idv.c_str(), nullptr, 10);
        auto it = docs.find(id);
        if (it != docs.end()) it->second = merge_objects(it->second, d);
      } else if (kind == "\"d\"") {
        docs.erase(strtoll(idv.c_str(), nullptr, 10));
      } else if (kind == "\"n\"") {
        long long nv = strtoll(v.c_str(), nullptr, 10);
        if (nv - 1 > max_seen) max_seen = nv - 1;
      }
      good_end = line_end;
    }
    if (!pending.empty()) torn = true;  // unterminated tail bytes
    fclose(in);
    if (damaged) {
      set_error("corrupt WAL " + path +
                ": invalid record followed by valid records "
                "(mid-file damage), refusing to open");
      return false;
    }
    if (torn) {
      if (truncate(path.c_str(), good_end) != 0) {
        set_error("cannot truncate torn WAL tail of " + path + ": " +
                  strerror(errno));
        return false;
      }
    }
    next_id = max_seen + 1;
    return true;
  }

  bool open_log() {
    fh = fopen(path.c_str(), "a");
    if (!fh) {
      set_error("cannot open WAL " + path + ": " + strerror(errno));
      return false;
    }
    return true;
  }

  void append(const std::string &line) {
    if (!fh) return;  // collection dropped while an op held its pointer
    fwrite(line.data(), 1, line.size(), fh);
    fputc('\n', fh);
    fflush(fh);
    if (durable) fsync(fileno(fh));
  }
};

struct Store {
  std::string root;
  bool durable;
  std::mutex mu;
  // shared_ptr: lods_drop may race an op that already fetched the
  // collection — it must stay alive until the last holder releases it.
  std::unordered_map<std::string, std::shared_ptr<Collection>> colls;

  std::shared_ptr<Collection> get(const std::string &name, bool create) {
    std::lock_guard<std::mutex> lock(mu);
    auto it = colls.find(name);
    if (it != colls.end()) return it->second;
    if (!create) {
      set_error("no such collection: " + name);
      return nullptr;
    }
    if (!valid_name(name)) {
      set_error("invalid collection name: " + name);
      return nullptr;
    }
    auto coll = std::make_shared<Collection>();
    coll->path = root + "/" + name + ".wal";
    coll->durable = durable;
    if (!coll->replay()) return nullptr;  // mid-file corruption
    if (!coll->open_log()) return nullptr;
    colls.emplace(name, coll);
    return coll;
  }
};

std::mutex g_handles_mu;
// shared_ptr: lods_close may race an in-flight op on another thread that
// already fetched the store — the op's copy keeps the Store alive until
// it returns (same pattern as Collection handles above).
std::vector<std::shared_ptr<Store>> g_handles;

std::shared_ptr<Store> store_for(int64_t h) {
  std::lock_guard<std::mutex> lock(g_handles_mu);
  if (h < 0 || h >= (int64_t)g_handles.size() || !g_handles[h]) {
    set_error("invalid store handle");
    return nullptr;
  }
  return g_handles[h];
}

char *dup_buffer(const std::string &s, int64_t *out_len) {
  char *buf = (char *)malloc(s.size() + 1);
  memcpy(buf, s.data(), s.size());
  buf[s.size()] = 0;
  if (out_len) *out_len = (int64_t)s.size();
  return buf;
}

// ---------------------------------------------------------------------------
// CSV parsing (RFC 4180: quoted fields, "" escapes, embedded newlines)
// ---------------------------------------------------------------------------

void json_escape(const std::string &in, std::string &out) {
  out += '"';
  for (unsigned char c : in) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char esc[8];
          snprintf(esc, sizeof esc, "\\u%04x", c);
          out += esc;
        } else {
          out += (char)c;
        }
    }
  }
  out += '"';
}

// Shortest float formatting that round-trips (json.dumps parity-ish).
void format_double(double v, std::string &out) {
  char buf[40];
  for (int prec = 15; prec <= 17; prec++) {
    snprintf(buf, sizeof buf, "%.*g", prec, v);
    if (strtod(buf, nullptr) == v) break;
  }
  out += buf;
}

// Append the inferred-JSON form of a CSV cell.
// ONE whitespace set for every ingest-parity path (Python str.strip's
// ASCII subset): infer_value's empty/trailing checks and the chunk
// parser's cell trim must use the same predicate or the engines'
// semantics drift (the backends-interchangeable contract,
// services/dataset.py::_infer).
inline bool is_ascii_ws(char ch) {
  return ch == ' ' || ch == '\t' || ch == '\n' || ch == '\r' ||
         ch == '\v' || ch == '\f';
}

void infer_value(const std::string &cell, std::string &out) {
  // Whitespace-only counts as empty → null, matching the Python
  // path's _infer (services/dataset.py) and the numeric chunk
  // parser's trim: a cell of spaces is an empty cell, not a string.
  bool all_ws = true;
  for (char ch : cell) {
    if (!is_ascii_ws(ch)) {
      all_ws = false;
      break;
    }
  }
  if (all_ws) {
    out += "null";
    return;
  }
  const char *s = cell.c_str();
  char *end = nullptr;
  errno = 0;
  long long iv = strtoll(s, &end, 10);
  if (errno == 0 && end != s) {
    const char *p = end;
    while (is_ascii_ws(*p)) p++;
    if (*p == 0) {  // fully consumed (allowing trailing whitespace)
      char buf[32];
      snprintf(buf, sizeof buf, "%lld", iv);
      out += buf;
      return;
    }
  }
  errno = 0;
  end = nullptr;
  double dv = strtod(s, &end);
  bool consumed = end && (end != s);
  if (consumed) {
    while (is_ascii_ws(*end)) end++;
    consumed = (*end == 0);
  }
  // Reject inf/nan spellings (not valid JSON) and partial parses.
  if (consumed && errno == 0 && dv == dv && dv <= 1.7976931348623157e308 &&
      dv >= -1.7976931348623157e308) {
    // Only treat as a number if it LOOKS numeric (strtod accepts "0x...",
    // "inf", "nan" — Python float() accepts inf/nan but those aren't JSON).
    const char *digits = (s[0] == '+' || s[0] == '-') ? s + 1 : s;
    char c0 = digits[0];
    if ((c0 >= '0' && c0 <= '9') || c0 == '.') {
      bool hexish =
          c0 == '0' && (digits[1] == 'x' || digits[1] == 'X');
      if (!hexish) {
        format_double(dv, out);
        return;
      }
    }
  }
  json_escape(cell, out);
}

void clean_header(std::vector<std::string> &header) {
  for (size_t i = 0; i < header.size(); i++) {
    std::string &h = header[i];
    // strip
    size_t a = 0, b = h.size();
    while (a < b && std::isspace((unsigned char)h[a])) a++;
    while (b > a && std::isspace((unsigned char)h[b - 1])) b--;
    std::string cleaned;
    bool in_run = false;
    for (size_t j = a; j < b; j++) {
      unsigned char c = h[j];
      if (std::isalnum(c) || c == '_') {
        cleaned += (char)c;
        in_run = false;
      } else if (!in_run) {
        cleaned += '_';
        in_run = true;
      }
    }
    // strip leading/trailing underscores
    size_t s0 = cleaned.find_first_not_of('_');
    size_t s1 = cleaned.find_last_not_of('_');
    cleaned = (s0 == std::string::npos)
                  ? ""
                  : cleaned.substr(s0, s1 - s0 + 1);
    if (cleaned.empty()) {
      char buf[24];
      snprintf(buf, sizeof buf, "col%zu", i);
      cleaned = buf;
    }
    h = cleaned;
  }
}

// Parse one CSV record starting at *pos; returns false at EOF.
// *clean_end (optional) reports whether the record terminated on an
// UNQUOTED newline — chunked callers roll back records that merely ran
// out of buffer (possibly inside a quoted field containing '\n').
bool next_record(const char *s, size_t n, size_t *pos,
                 std::vector<std::string> &fields,
                 bool *clean_end = nullptr) {
  fields.clear();
  size_t i = *pos;
  if (clean_end) *clean_end = false;
  if (i >= n) return false;
  std::string cur;
  bool in_quotes = false, any = false;
  while (i < n) {
    char c = s[i];
    if (in_quotes) {
      if (c == '"') {
        if (i + 1 < n && s[i + 1] == '"') {
          cur += '"';
          i += 2;
        } else {
          in_quotes = false;
          i++;
        }
      } else {
        cur += c;
        i++;
      }
      continue;
    }
    if (c == '"') {
      in_quotes = true;
      any = true;
      i++;
    } else if (c == ',') {
      fields.push_back(cur);
      cur.clear();
      any = true;
      i++;
    } else if (c == '\n' || c == '\r') {
      if (c == '\r' && i + 1 < n && s[i + 1] == '\n') i++;
      i++;
      if (clean_end) *clean_end = true;
      break;
    } else {
      cur += c;
      any = true;
      i++;
    }
  }
  *pos = i;
  if (!any && cur.empty() && fields.empty()) {
    // blank line: report as empty record (caller skips)
    return true;
  }
  fields.push_back(cur);
  return true;
}

// Parse one TRIMMED numeric cell in [a, b), no allocation —
// services/dataset.py::_infer semantics exactly: no '_'/hex spellings,
// inf/nan results (incl. overflow) are non-numeric, a leading '+' is
// fine, subnormal underflow is a fine number.  On success *v holds the
// value and *int_format reports the dtype-parity classification (pure
// [+-]?digits fitting int64).  Shared by the fast (in-place) and slow
// (quote-aware) record paths so their semantics cannot drift.
bool parse_numeric_cell(const char *a, const char *b, double *v,
                        bool *int_format) {
  size_t m = (size_t)(b - a);
  size_t digit_start = (a[0] == '+' || a[0] == '-') ? 1 : 0;
  bool ifmt = digit_start < m;
  size_t n_digits = 0;
  for (size_t j = 0; j < m; j++) {
    char ch = a[j];
    if (ch == '_' || ch == 'x' || ch == 'X') return false;
    if (j >= digit_start) {
      if (ch >= '0' && ch <= '9')
        n_digits++;
      else
        ifmt = false;
    }
  }
  double val = 0.0;
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  const char *p = a;
  if (*p == '+') {
    // std::from_chars rejects the leading '+' strtod accepts; skip it
    // only when what follows could start a number, so "+-5" still
    // fails exactly like strtod's end-pointer check did.
    if (m < 2 || (!(p[1] >= '0' && p[1] <= '9') && p[1] != '.'))
      return false;
    p++;
  }
  auto res = std::from_chars(p, b, val);
  if (res.ec == std::errc::result_out_of_range) {
    // from_chars can't distinguish overflow (non-numeric by contract)
    // from underflow-to-subnormal (accepted); rare — resolve with the
    // old NUL-terminated strtod exactly.
    std::string copy(a, m);
    char *end = nullptr;
    errno = 0;
    val = strtod(copy.c_str(), &end);
    if (end != copy.c_str() + copy.size() || val != val ||
        val > 1.7976931348623157e308 || val < -1.7976931348623157e308)
      return false;
  } else if (res.ec != std::errc() || res.ptr != b) {
    return false;
  } else if (val != val || val > 1.7976931348623157e308 ||
             val < -1.7976931348623157e308) {
    return false;  // "inf"/"nan" spellings parse but are non-numeric
  }
#else
  // Pre-GCC-11 libstdc++ has no floating-point from_chars: same
  // semantics via a NUL-terminated strtod copy (slower, still correct
  // — better than the whole native engine silently failing to build).
  {
    std::string copy(a, m);
    char *end = nullptr;
    val = strtod(copy.c_str(), &end);
    if (end == copy.c_str() || end != copy.c_str() + copy.size() ||
        val != val || val > 1.7976931348623157e308 ||
        val < -1.7976931348623157e308)
      return false;
  }
#endif
  if (ifmt && n_digits >= 19) {
    // 18 digits always fit int64 (max ~9.2e18); only longer runs need
    // the overflow probe.
    std::string copy(a, m);
    errno = 0;
    (void)strtoll(copy.c_str(), nullptr, 10);
    if (errno == ERANGE) ifmt = false;
  }
  *v = val;
  if (int_format) *int_format = ifmt;
  return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

const char *lods_last_error(void) { return g_error.c_str(); }

void lods_free(char *p) { free(p); }

int64_t lods_open(const char *root, int durable) {
  struct stat st;
  if (stat(root, &st) != 0) {
    if (mkdir(root, 0777) != 0 && errno != EEXIST) {
      set_error(std::string("cannot create root: ") + strerror(errno));
      return -1;
    }
  }
  auto store = std::make_shared<Store>();
  store->root = root;
  store->durable = durable != 0;
  // Open existing collections eagerly (mirrors DocumentStore.__init__).
  DIR *dir = opendir(root);
  if (dir) {
    struct dirent *ent;
    std::vector<std::string> names;
    while ((ent = readdir(dir)) != nullptr) {
      std::string fn = ent->d_name;
      if (fn.size() > 4 && fn.substr(fn.size() - 4) == ".wal")
        names.push_back(fn.substr(0, fn.size() - 4));
    }
    closedir(dir);
    for (auto &nm : names) {
      if (!store->get(nm, true)) {
        // Mid-file WAL corruption: refuse the whole open, loudly —
        // silently skipping the collection would read as data loss
        // (mirrors DocumentStore.__init__ raising CorruptWal).
        return -1;
      }
    }
  }
  std::lock_guard<std::mutex> lock(g_handles_mu);
  g_handles.push_back(std::move(store));
  return (int64_t)g_handles.size() - 1;
}

int lods_close(int64_t h) {
  std::lock_guard<std::mutex> lock(g_handles_mu);
  if (h < 0 || h >= (int64_t)g_handles.size() || !g_handles[h]) return -1;
  g_handles[h].reset();
  return 0;
}

int lods_has_collection(int64_t h, const char *name) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::lock_guard<std::mutex> lock(st->mu);
  return st->colls.count(name) ? 1 : 0;
}

char *lods_list_collections(int64_t h, int64_t *out_len) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return nullptr;
  std::vector<std::string> names;
  {
    std::lock_guard<std::mutex> lock(st->mu);
    for (auto &kv : st->colls) names.push_back(kv.first);
  }
  std::sort(names.begin(), names.end());
  std::string out;
  for (auto &nm : names) {
    out += nm;
    out += '\n';
  }
  return dup_buffer(out, out_len);
}

// Insert JSONL docs (no _id fields); returns count, sets *first_id.
int64_t lods_insert_many(int64_t h, const char *name, const char *jsonl,
                         int64_t len, long long *first_id) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::shared_ptr<Collection> coll = st->get(name, true);
  if (!coll) return -1;
  std::lock_guard<std::mutex> lock(coll->mu);
  std::string batch;
  batch.reserve((size_t)len + 64);
  int64_t count = 0;
  size_t i = 0, n = (size_t)len;
  if (first_id) *first_id = coll->next_id;
  while (i < n) {
    size_t j = i;
    while (j < n && jsonl[j] != '\n') j++;
    if (j > i) {
      std::string doc(jsonl + i, j - i);
      long long id = coll->next_id++;
      doc = with_id(doc, id);
      coll->docs[id] = doc;
      batch += "{\"op\":\"i\",\"d\":";
      batch += doc;
      batch += "}\n";
      count++;
    }
    i = j + 1;
  }
  if (!batch.empty() && coll->fh) {
    fwrite(batch.data(), 1, batch.size(), coll->fh);
    fflush(coll->fh);
    if (coll->durable) fsync(fileno(coll->fh));
  }
  return count;
}

// Insert a single doc at an explicit id.  unique=1 -> fail if id exists
// (returns -2, the DuplicateKey signal).
int lods_insert_at(int64_t h, const char *name, const char *json,
                   long long id, int unique) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::shared_ptr<Collection> coll = st->get(name, true);
  if (!coll) return -1;
  std::lock_guard<std::mutex> lock(coll->mu);
  if (unique && coll->docs.count(id)) {
    set_error("duplicate _id");
    return -2;
  }
  std::string doc = with_id(json, id);
  coll->docs[id] = doc;
  if (id + 1 > coll->next_id) coll->next_id = id + 1;
  coll->append("{\"op\":\"i\",\"d\":" + doc + "}");
  return 0;
}

int lods_update(int64_t h, const char *name, long long id,
                const char *fields_json) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::shared_ptr<Collection> coll = st->get(name, false);
  if (!coll) return -1;
  std::lock_guard<std::mutex> lock(coll->mu);
  auto it = coll->docs.find(id);
  if (it == coll->docs.end()) return 0;
  it->second = merge_objects(it->second, fields_json);
  char idbuf[32];
  snprintf(idbuf, sizeof idbuf, "%lld", id);
  coll->append(std::string("{\"op\":\"u\",\"id\":") + idbuf + ",\"d\":" +
               fields_json + "}");
  return 1;
}

int lods_delete(int64_t h, const char *name, long long id) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::shared_ptr<Collection> coll = st->get(name, false);
  if (!coll) return -1;
  std::lock_guard<std::mutex> lock(coll->mu);
  if (!coll->docs.erase(id)) return 0;
  char idbuf[32];
  snprintf(idbuf, sizeof idbuf, "%lld", id);
  coll->append(std::string("{\"op\":\"d\",\"id\":") + idbuf + "}");
  return 1;
}

char *lods_find_one(int64_t h, const char *name, long long id,
                    int64_t *out_len) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return nullptr;
  std::shared_ptr<Collection> coll = st->get(name, false);
  if (!coll) return nullptr;
  std::lock_guard<std::mutex> lock(coll->mu);
  auto it = coll->docs.find(id);
  if (it == coll->docs.end()) {
    if (out_len) *out_len = 0;
    return nullptr;
  }
  return dup_buffer(it->second, out_len);
}

// All docs in _id order as JSONL, with skip/limit (-1 = no limit).
char *lods_scan(int64_t h, const char *name, int64_t skip, int64_t limit,
                int64_t *out_len) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return nullptr;
  std::shared_ptr<Collection> coll = st->get(name, false);
  if (!coll) return nullptr;
  std::lock_guard<std::mutex> lock(coll->mu);
  std::string out;
  int64_t seen = 0, emitted = 0;
  for (auto &kv : coll->docs) {
    if (seen++ < skip) continue;
    if (limit >= 0 && emitted >= limit) break;
    out += kv.second;
    out += '\n';
    emitted++;
  }
  return dup_buffer(out, out_len);
}

int64_t lods_count(int64_t h, const char *name) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::shared_ptr<Collection> coll = st->get(name, false);
  if (!coll) return -1;
  std::lock_guard<std::mutex> lock(coll->mu);
  return (int64_t)coll->docs.size();
}

long long lods_next_id(int64_t h, const char *name) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::shared_ptr<Collection> coll = st->get(name, false);
  if (!coll) return -1;
  std::lock_guard<std::mutex> lock(coll->mu);
  return coll->next_id;
}

// Numerically-equal JSON numbers (1 vs 1.0 vs 1e0 — e.g. after a
// dataType cast wrote floats next to originally-ingested ints) must
// share one histogram bucket, as the Python backend's parsed-value
// grouping does.  Non-numeric values (quoted strings, objects, bools)
// pass through untouched.
static std::string canonical_count_key(const std::string &val) {
  errno = 0;
  char *end = nullptr;
  double d = strtod(val.c_str(), &end);
  if (end == val.c_str() || *end != '\0' || errno == ERANGE) return val;
  // Magnitude guard FIRST: (long long)d on an out-of-range double
  // (1e300, inf) is undefined behavior.  Beyond 2^53 doubles alias
  // distinct integers, so a pure INTEGER literal keeps its raw text —
  // Python's exact ints keep such values in separate buckets and so
  // must we.  Float-syntax spellings ('.', 'e', 'E') are already
  // doubles on the Python side too, so %.17g canonicalization is safe
  // (and merges 1e20 with 1E+20).
  if (std::fabs(d) >= 9e15 &&
      val.find_first_of(".eE") == std::string::npos)
    return val;
  char buf[64];
  if (std::fabs(d) < 9e15 && d == (double)(long long)d) {
    snprintf(buf, sizeof buf, "%lld", (long long)d);
  } else {
    snprintf(buf, sizeof buf, "%.17g", d);
  }
  return buf;
}

// Value-count aggregation over a top-level field (histogram service's
// $group/$sum).  Output: JSONL lines {"k":<canonical value>,"n":<count>}.
// Skips _id=0 (metadata) and docs with docType=="execution", matching
// DocumentStore.aggregate_counts.
char *lods_value_counts(int64_t h, const char *name, const char *field,
                        int64_t *out_len) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return nullptr;
  std::shared_ptr<Collection> coll = st->get(name, false);
  if (!coll) return nullptr;
  std::lock_guard<std::mutex> lock(coll->mu);
  std::map<std::string, int64_t> counts;
  std::vector<std::string> order;  // first-seen order for stable output
  for (auto &kv : coll->docs) {
    if (kv.first == 0) continue;
    std::string dt;
    if (get_field(kv.second, "docType", dt) && dt == "\"execution\"")
      continue;
    std::string val;
    if (!get_field(kv.second, field, val)) val = "null";
    val = canonical_count_key(val);
    auto it = counts.find(val);
    if (it == counts.end()) {
      counts.emplace(val, 1);
      order.push_back(val);
    } else {
      it->second++;
    }
  }
  std::string out;
  for (auto &key : order) {
    out += "{\"k\":";
    out += key;
    out += ",\"n\":";
    char buf[32];
    snprintf(buf, sizeof buf, "%" PRId64, counts[key]);
    out += buf;
    out += "}\n";
  }
  return dup_buffer(out, out_len);
}

int lods_drop(int64_t h, const char *name) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::shared_ptr<Collection> coll;
  {
    std::lock_guard<std::mutex> lock(st->mu);
    auto it = st->colls.find(name);
    if (it == st->colls.end()) return 0;
    coll = it->second;
    st->colls.erase(it);
  }
  // In-flight ops still holding the shared_ptr serialize on mu; after
  // this, their writes hit the fh==nullptr guard and become no-ops.
  std::lock_guard<std::mutex> lock(coll->mu);
  if (coll->fh) {
    fclose(coll->fh);
    coll->fh = nullptr;
  }
  unlink(coll->path.c_str());
  return 1;
}

int lods_compact(int64_t h, const char *name) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::shared_ptr<Collection> coll = st->get(name, false);
  if (!coll) return -1;
  std::lock_guard<std::mutex> lock(coll->mu);
  if (!coll->fh) {
    set_error("collection dropped");
    return -1;
  }
  std::string tmp_path = coll->path + ".tmp";
  FILE *tmp = fopen(tmp_path.c_str(), "w");
  if (!tmp) {
    set_error(std::string("cannot open tmp: ") + strerror(errno));
    return -1;
  }
  char head[64];
  snprintf(head, sizeof head, "{\"op\": \"n\", \"v\": %lld}\n", coll->next_id);
  fwrite(head, 1, strlen(head), tmp);
  for (auto &kv : coll->docs) {
    std::string line = "{\"op\":\"i\",\"d\":" + kv.second + "}\n";
    fwrite(line.data(), 1, line.size(), tmp);
  }
  // Durability parity with the append path: fsync the rewritten file
  // BEFORE it replaces the live log, and the directory entry after —
  // a crash mid-compaction must never leave an empty collection where
  // a durable one stood.
  fflush(tmp);
  fsync(fileno(tmp));
  fclose(tmp);
  fclose(coll->fh);
  coll->fh = nullptr;
  if (rename(tmp_path.c_str(), coll->path.c_str()) != 0) {
    set_error(std::string("rename failed: ") + strerror(errno));
    coll->open_log();
    return -1;
  }
  std::string dir = coll->path.substr(0, coll->path.find_last_of('/'));
  int dfd = open(dir.empty() ? "." : dir.c_str(), O_RDONLY);
  if (dfd >= 0) {
    fsync(dfd);
    close(dfd);
  }
  return coll->open_log() ? 0 : -1;
}

// Project selected top-level fields of every data row of src into a new
// collection dst — the reference's Spark-executed column projection
// (projection_image/projection.py:20-48) as a native scan.  Skips the
// metadata doc (_id=0) and execution-ledger docs; missing fields become
// null (matching the Python path's d.get(f)).  fields_nl: '\n'-separated
// field names.  Returns rows written, or -1.
int64_t lods_project(int64_t h, const char *src_name, const char *dst_name,
                     const char *fields_nl) {
  std::shared_ptr<Store> st = store_for(h);
  if (!st) return -1;
  std::shared_ptr<Collection> src = st->get(src_name, false);
  if (!src) return -1;

  std::vector<std::string> fields;
  {
    const char *p = fields_nl;
    while (*p) {
      const char *q = p;
      while (*q && *q != '\n') q++;
      if (q > p) fields.emplace_back(p, q - p);
      p = *q ? q + 1 : q;
    }
  }

  // Snapshot the projected rows under the src lock, then release it
  // before taking the dst lock (no ordering between collections).
  std::vector<std::string> rows;
  {
    std::lock_guard<std::mutex> lock(src->mu);
    rows.reserve(src->docs.size());
    std::vector<KV> pairs;
    for (auto &kv : src->docs) {
      if (kv.first == 0) continue;
      pairs.clear();
      if (!parse_object(kv.second, pairs)) continue;
      bool is_exec = false;
      for (auto &pair : pairs) {
        if (pair.key == "docType" && pair.raw_val == "\"execution\"") {
          is_exec = true;
          break;
        }
      }
      if (is_exec) continue;
      std::string out = "{";
      for (size_t i = 0; i < fields.size(); i++) {
        if (i) out += ',';
        json_escape(fields[i], out);
        out += ':';
        const std::string *val = nullptr;
        for (auto &pair : pairs) {
          if (pair.key == fields[i]) {
            val = &pair.raw_val;
            break;
          }
        }
        out += val ? *val : "null";
      }
      out += "}";
      rows.push_back(std::move(out));
    }
  }

  std::shared_ptr<Collection> dst = st->get(dst_name, true);
  if (!dst) return -1;
  std::lock_guard<std::mutex> lock(dst->mu);
  std::string batch;
  for (auto &row : rows) {
    long long id = dst->next_id++;
    std::string doc = with_id(row, id);
    dst->docs[id] = doc;
    batch += "{\"op\":\"i\",\"d\":";
    batch += doc;
    batch += "}\n";
  }
  if (!batch.empty() && dst->fh) {
    fwrite(batch.data(), 1, batch.size(), dst->fh);
    fflush(dst->fh);
    if (dst->durable) fsync(fileno(dst->fh));
  }
  return (int64_t)rows.size();
}

// ---------------------------------------------------------------------------
// CSV → JSONL docs.  Output: first line is the cleaned header as a JSON
// array; each following line is a document object (no _id) ready for
// lods_insert_many.  infer=1 applies int/float/null inference (the
// dataset service's default); infer=0 keeps every value a string (the
// reference's raw behavior, database_api_image/database.py:124-137).
// ---------------------------------------------------------------------------

// Numeric chunk parse for SHARDED (beyond-RAM) ingest: complete CSV
// records from buf land row-major in out (ncols doubles per row).
// Empty/missing cells -> NaN; non-empty unparseable cells -> NaN AND
// bad_counts[col]++ (the Python writer's "column is not numeric"
// contract checks these); extra columns are ignored.  Unless is_final,
// a trailing record not terminated by a newline is NOT consumed — the
// caller re-feeds it with the next chunk (*consumed reports the bytes
// eaten).  Returns rows parsed, or -1 (see lods_last_error).
int64_t lods_csv_numeric_chunk(const char *buf, int64_t len, int is_final,
                               int64_t ncols, double *out,
                               int64_t max_rows, int64_t *bad_counts,
                               int64_t *float_counts, int64_t *consumed) {
  if (ncols <= 0 || max_rows < 0) {
    set_error("bad ncols/max_rows");
    return -1;
  }
  const double nan = std::numeric_limits<double>::quiet_NaN();
  std::vector<std::string> row;
  size_t pos = 0, n = (size_t)len;
  int64_t rows = 0;

  // Store one parsed cell with _infer-parity accounting.  The trim
  // strips the FULL ASCII whitespace set like Python's str.strip()
  // (_infer trims before parsing) — strtod's own leading-space skip
  // used to paper over '\v'/'\f', but from_chars does not skip, and
  // trailing whitespace must trim identically anyway.
  auto emit_cell = [&](const char *a, const char *b, double *slot,
                       int64_t c) {
    while (a < b && is_ascii_ws(*a)) a++;
    while (b > a && is_ascii_ws(b[-1])) b--;
    if (a == b) {
      *slot = nan;  // empty cell
      return;
    }
    double v;
    bool int_format;
    if (parse_numeric_cell(a, b, &v, &int_format)) {
      *slot = v;
      if (float_counts && !int_format) float_counts[c]++;
    } else {
      *slot = nan;
      if (bad_counts) bad_counts[c]++;
    }
  };

  while (rows < max_rows) {
    if (pos >= n) break;  // EOF
    size_t rec_begin = pos;

    // FAST PATH: records without quotes (the overwhelmingly common
    // CSV-of-numbers case) parse IN PLACE over the buffer — no
    // per-record string vector, no per-cell copies.  A '"' anywhere
    // before the terminator falls back to the quote-aware parser,
    // which owns every quoting subtlety (escaped quotes, newlines
    // inside quoted fields).
    size_t k = rec_begin;
    while (k < n && buf[k] != '"' && buf[k] != '\n' && buf[k] != '\r')
      k++;

    if (k < n && buf[k] == '"') {
      // SLOW PATH (quoted record) — semantics identical to pre-r4.
      bool clean_end = false;
      if (!next_record(buf, n, &pos, row, &clean_end)) break;
      if (!clean_end && !is_final) {
        // Ran out of buffer without an UNQUOTED newline (maybe inside
        // a quoted field containing '\n'): roll back, wait for bytes.
        pos = rec_begin;
        break;
      }
      if (row.empty() || (row.size() == 1 && row[0].empty()))
        continue;  // blank line
      double *dst = out + rows * ncols;
      for (int64_t c = 0; c < ncols; c++) {
        if ((size_t)c >= row.size()) {
          dst[c] = nan;  // short row pads NaN (Python parity)
          continue;
        }
        const std::string &cell = row[c];
        emit_cell(cell.data(), cell.data() + cell.size(), dst + c, c);
      }
      rows++;
      continue;
    }

    size_t rec_end = k;
    if (k < n) {  // terminated on '\n' or '\r'
      pos = (buf[k] == '\r' && k + 1 < n && buf[k + 1] == '\n')
                ? k + 2
                : k + 1;
    } else if (!is_final) {
      break;  // torn tail: leave pos at rec_begin, wait for bytes
    } else {
      pos = n;  // final chunk: the unterminated tail is a record
    }
    if (rec_end == rec_begin) continue;  // blank line

    double *dst = out + rows * ncols;
    const char *cell_begin = buf + rec_begin;
    const char *end = buf + rec_end;
    int64_t c = 0;
    while (c < ncols) {
      const char *cell_end = cell_begin;
      while (cell_end < end && *cell_end != ',') cell_end++;
      emit_cell(cell_begin, cell_end, dst + c, c);
      c++;
      if (cell_end >= end) break;  // last cell of the record
      cell_begin = cell_end + 1;
    }
    for (; c < ncols; c++) dst[c] = nan;  // short row pads NaN
    rows++;
  }
  if (consumed) *consumed = (int64_t)pos;
  return rows;
}

char *lods_csv_parse(const char *buf, int64_t len, int infer,
                     int64_t *out_len) {
  std::vector<std::string> header, row;
  size_t pos = 0;
  size_t n = (size_t)len;
  // Skip UTF-8 BOM.
  if (n >= 3 && (unsigned char)buf[0] == 0xEF && (unsigned char)buf[1] == 0xBB &&
      (unsigned char)buf[2] == 0xBF)
    pos = 3;
  if (!next_record(buf, n, &pos, header) || header.empty()) {
    set_error("empty CSV input");
    return nullptr;
  }
  clean_header(header);
  std::string out;
  out.reserve((size_t)len + (size_t)len / 2);
  out += '[';
  for (size_t i = 0; i < header.size(); i++) {
    if (i) out += ',';
    json_escape(header[i], out);
  }
  out += "]\n";
  while (next_record(buf, n, &pos, row)) {
    if (row.empty()) continue;  // blank line
    out += '{';
    size_t cols = row.size() < header.size() ? row.size() : header.size();
    for (size_t i = 0; i < cols; i++) {
      if (i) out += ',';
      json_escape(header[i], out);
      out += ':';
      if (infer)
        infer_value(row[i], out);
      else
        json_escape(row[i], out);
    }
    out += "}\n";
  }
  return dup_buffer(out, out_len);
}

}  // extern "C"
