// Concurrency stress driver for the native document store, built with
// -fsanitize=thread (see Makefile `tsan` target).  Hammers the C ABI
// from many threads with overlapping inserts/reads/updates/aggregates
// plus a drop racing live readers — the use-after-free class TSAN is
// here to catch.  Exit code 0 + no TSAN report = pass.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int64_t lods_open(const char *root, int durable);
int lods_close(int64_t h);
int64_t lods_insert_many(int64_t h, const char *name, const char *jsonl,
                         int64_t len, long long *first_id);
int lods_insert_at(int64_t h, const char *name, const char *json,
                   long long id, int unique);
int lods_update(int64_t h, const char *name, long long id,
                const char *fields_json);
int lods_delete(int64_t h, const char *name, long long id);
char *lods_find_one(int64_t h, const char *name, long long id,
                    int64_t *out_len);
char *lods_scan(int64_t h, const char *name, int64_t skip, int64_t limit,
                int64_t *out_len);
char *lods_value_counts(int64_t h, const char *name, const char *field,
                        int64_t *out_len);
int64_t lods_count(int64_t h, const char *name);
int lods_drop(int64_t h, const char *name);
int lods_compact(int64_t h, const char *name);
void lods_free(char *p);
}

int main(int argc, char **argv) {
  const char *root = argc > 1 ? argv[1] : "/tmp/lods_stress";
  int64_t h = lods_open(root, 0);
  if (h < 0) {
    fprintf(stderr, "open failed\n");
    return 1;
  }

  const int kThreads = 8, kOps = 400;
  std::vector<std::thread> threads;

  // Writers + readers on a shared collection.
  for (int t = 0; t < kThreads; t++) {
    threads.emplace_back([h, t]() {
      char doc[64];
      for (int i = 0; i < kOps; i++) {
        snprintf(doc, sizeof doc, "{\"t\":%d,\"i\":%d}\n", t, i);
        long long first = 0;
        lods_insert_many(h, "shared", doc, (int64_t)strlen(doc), &first);
        if (i % 7 == 0) {
          lods_update(h, "shared", first, "{\"seen\":true}");
        }
        if (i % 5 == 0) {
          int64_t n = 0;
          char *buf = lods_scan(h, "shared", 0, 16, &n);
          lods_free(buf);
        }
        if (i % 11 == 0) {
          int64_t n = 0;
          char *buf = lods_value_counts(h, "shared", "t", &n);
          lods_free(buf);
        }
        if (i % 13 == 0) lods_count(h, "shared");
      }
    });
  }

  // Drop racing live readers/writers on a churn collection.
  threads.emplace_back([h]() {
    for (int round = 0; round < 50; round++) {
      char doc[32];
      snprintf(doc, sizeof doc, "{\"r\":%d}\n", round);
      long long first = 0;
      lods_insert_many(h, "churn", doc, (int64_t)strlen(doc), &first);
      lods_drop(h, "churn");
    }
  });
  threads.emplace_back([h]() {
    for (int round = 0; round < 200; round++) {
      int64_t n = 0;
      char *buf = lods_scan(h, "churn", 0, -1, &n);
      lods_free(buf);
      char doc[32] = "{\"w\":1}\n";
      long long first = 0;
      lods_insert_many(h, "churn", doc, (int64_t)strlen(doc), &first);
    }
  });
  // Compaction racing everything.
  threads.emplace_back([h]() {
    for (int round = 0; round < 20; round++) {
      lods_compact(h, "shared");
    }
  });

  for (auto &th : threads) th.join();

  int64_t total = lods_count(h, "shared");
  if (total != (int64_t)kThreads * kOps) {
    fprintf(stderr, "count mismatch: %lld\n", (long long)total);
    return 2;
  }
  lods_close(h);
  printf("stress ok: %lld docs\n", (long long)total);
  return 0;
}
