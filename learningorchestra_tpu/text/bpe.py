"""Byte-pair-encoding tokenizer, trained from a streaming word counter.

Classic BPE (Sennrich et al. 2016 — PAPERS.md lists the public recipe):
start from characters, repeatedly merge the most frequent adjacent
symbol pair across the corpus, stop at ``vocab_size``.  Training is
incremental-count (pair counts updated only for the word types a merge
touched), so cost scales with the words a merge actually changes, not
with the whole vocabulary per merge.

Design constraints this implementation serves:

- **Counter-in, rows-out**: training consumes a ``{word: count}``
  mapping — ``count_words`` builds it from any row iterable without
  retaining the rows, so only the vocabulary of word TYPES stays
  resident here.  (The text parent itself is a document-store dataset,
  which is RAM-resident by design — see services/transform.py.)
- **TPU-facing output**: ``encode`` returns a fixed-length int32 row
  ``[BOS, tok..., EOS, PAD...]``; pad id is 0 to match the model zoo's
  key-mask convention (``tokens != 0`` — models/text.py pad_mask).
- **Deterministic artifacts**: ties in pair frequency break
  lexicographically, so the same corpus always yields the same merges,
  and ``to_json``/``from_json`` round-trip the whole tokenizer for
  artifact storage and later re-use on held-out splits.
"""

from __future__ import annotations

import json
import re
from collections import Counter

import numpy as np

PAD_ID = 0
UNK_ID = 1
BOS_ID = 2
EOS_ID = 3
_SPECIALS = ("<pad>", "<unk>", "<s>", "</s>")
_EOW = "</w>"  # end-of-word marker: makes merges word-boundary-aware

_WORD_RE = re.compile(r"\w+|[^\w\s]", re.UNICODE)


def pretokenize(text: str, *, lowercase: bool = True) -> list[str]:
    """Split into words + punctuation (the BPE alphabet's units)."""
    if lowercase:
        text = text.lower()
    return _WORD_RE.findall(text)


def count_words(texts, *, lowercase: bool = True) -> Counter:
    """Streaming word counter — feed it row by row; only the counter
    (vocabulary of word TYPES, not the corpus) stays in memory."""
    counts: Counter = Counter()
    for text in texts:
        counts.update(pretokenize(str(text), lowercase=lowercase))
    return counts


class BpeTokenizer:
    def __init__(self, vocab: dict[str, int], merges: list[tuple[str, str]],
                 *, lowercase: bool = True):
        self.vocab = dict(vocab)
        self.merges = [tuple(m) for m in merges]
        self.lowercase = lowercase
        self._ranks = {m: i for i, m in enumerate(self.merges)}
        self._word_cache: dict[str, list[int]] = {}

    # -- training ----------------------------------------------------------

    @classmethod
    def train(cls, word_counts: Counter | dict, *, vocab_size: int = 8000,
              lowercase: bool = True) -> "BpeTokenizer":
        """Learn merges until the vocab reaches ``vocab_size`` (or no
        pair repeats).  Incremental pair bookkeeping: each merge only
        re-scans the word types that contain the merged pair."""
        if vocab_size < len(_SPECIALS) + 1:
            raise ValueError(f"vocab_size too small: {vocab_size}")
        # Word types as symbol tuples, weighted by corpus count.
        words: list[list[str]] = []
        counts: list[int] = []
        for w, c in word_counts.items():
            if not w:
                continue
            words.append(list(w) + [_EOW])
            counts.append(int(c))

        # pair -> total count; pair -> {word indices containing it}
        pair_counts: Counter = Counter()
        pair_words: dict[tuple[str, str], set[int]] = {}
        for i, syms in enumerate(words):
            for a, b in zip(syms, syms[1:]):
                pair_counts[(a, b)] += counts[i]
                pair_words.setdefault((a, b), set()).add(i)

        alphabet = sorted({s for syms in words for s in syms})
        merges: list[tuple[str, str]] = []
        n_tokens = len(_SPECIALS) + len(alphabet)
        if n_tokens > vocab_size:
            # Specials + the full corpus alphabet are always in the
            # vocab, so a smaller request can't be honored — and
            # letting ids overflow the requested size silently breaks
            # the downstream embedding gather (XLA clamps indices).
            raise ValueError(
                f"vocab_size={vocab_size} is smaller than the corpus "
                f"alphabet ({len(alphabet)} symbols + "
                f"{len(_SPECIALS)} specials = {n_tokens}); raise "
                "vocab_size to at least that"
            )

        # Best-pair selection via a lazy-invalidation max-heap: a full
        # max() over pair_counts per merge would be O(#distinct pairs)
        # per iteration — minutes of pure Python at IMDb scale.  Heap
        # entries go stale when counts change; pop-and-check against
        # the live count until the top is current.  Equal counts pop
        # the lexicographically smallest pair — any total order works,
        # it only has to be deterministic.
        import heapq

        heap = [(-c, p) for p, c in pair_counts.items()]
        heapq.heapify(heap)

        while n_tokens + len(merges) < vocab_size and heap:
            negc, best = heapq.heappop(heap)
            if pair_counts.get(best) != -negc:
                continue  # stale entry; the live count was re-pushed
            a, b = best
            freq = -negc
            if freq < 2:
                break  # merging singletons only memorizes the corpus
            merges.append((a, b))
            merged = a + b
            # Re-tokenize ONLY the affected word types, updating the
            # pair books by delta; every touched pair re-enters the
            # heap with its new count after the merge.
            changed: set[tuple[str, str]] = set()
            for i in sorted(pair_words.get((a, b), ())):
                syms = words[i]
                c = counts[i]
                for x, y in zip(syms, syms[1:]):
                    pair_counts[(x, y)] -= c
                    changed.add((x, y))
                    if pair_counts[(x, y)] <= 0:
                        del pair_counts[(x, y)]
                    s = pair_words.get((x, y))
                    if s:
                        s.discard(i)
                out = []
                j = 0
                while j < len(syms):
                    if (j + 1 < len(syms) and syms[j] == a
                            and syms[j + 1] == b):
                        out.append(merged)
                        j += 2
                    else:
                        out.append(syms[j])
                        j += 1
                words[i] = out
                for x, y in zip(out, out[1:]):
                    pair_counts[(x, y)] += c
                    changed.add((x, y))
                    pair_words.setdefault((x, y), set()).add(i)
            for p in changed:
                c = pair_counts.get(p)
                if c:
                    heapq.heappush(heap, (-c, p))

        vocab: dict[str, int] = {s: i for i, s in enumerate(_SPECIALS)}
        for s in alphabet:
            vocab[s] = len(vocab)
        for a, b in merges:
            tok = a + b
            if tok not in vocab:
                vocab[tok] = len(vocab)
        return cls(vocab, merges, lowercase=lowercase)

    # -- encoding ----------------------------------------------------------

    def _bpe_word(self, word: str) -> list[int]:
        cached = self._word_cache.get(word)
        if cached is not None:
            return cached
        syms = list(word) + [_EOW]
        # Repeatedly apply the lowest-rank merge present in the word —
        # replays training order, so encoding matches training exactly.
        while len(syms) > 1:
            ranked = [
                (self._ranks.get((x, y)), k)
                for k, (x, y) in enumerate(zip(syms, syms[1:]))
            ]
            ranked = [(r, k) for r, k in ranked if r is not None]
            if not ranked:
                break
            _, k = min(ranked)
            syms = syms[:k] + [syms[k] + syms[k + 1]] + syms[k + 2:]
        ids = [self.vocab.get(s, UNK_ID) for s in syms]
        if len(self._word_cache) < 1_000_000:
            self._word_cache[word] = ids
        return ids

    def encode(self, text: str, max_len: int) -> np.ndarray:
        """``[BOS, tokens..., EOS]`` padded (id 0) / truncated to
        ``max_len`` — the fixed-shape contract the jitted train step
        needs.  Truncation keeps the head (BERT convention) and always
        terminates with EOS."""
        ids = [BOS_ID]
        for w in pretokenize(text, lowercase=self.lowercase):
            ids.extend(self._bpe_word(w))
            if len(ids) >= max_len:  # early stop: row is full anyway
                break
        ids = ids[: max_len - 1] + [EOS_ID]
        out = np.full((max_len,), PAD_ID, dtype=np.int32)
        out[: len(ids)] = ids
        return out

    def encode_batch(self, texts, max_len: int) -> np.ndarray:
        return np.stack([self.encode(str(t), max_len) for t in texts])

    def decode(self, ids) -> str:
        inv = getattr(self, "_inv", None)
        if inv is None:
            inv = self._inv = {i: s for s, i in self.vocab.items()}
        words, cur = [], ""
        for i in np.asarray(ids).reshape(-1).tolist():
            if i in (PAD_ID, BOS_ID):
                continue
            if i == EOS_ID:
                break
            tok = inv.get(int(i), "")
            if tok.endswith(_EOW):
                words.append(cur + tok[: -len(_EOW)])
                cur = ""
            else:
                cur += tok
        if cur:
            words.append(cur)
        return " ".join(words)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    # -- persistence -------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "vocab": self.vocab,
            "merges": [list(m) for m in self.merges],
            "lowercase": self.lowercase,
        })

    @classmethod
    def from_json(cls, blob: str) -> "BpeTokenizer":
        d = json.loads(blob)
        return cls(d["vocab"], [tuple(m) for m in d["merges"]],
                   lowercase=d.get("lowercase", True))
