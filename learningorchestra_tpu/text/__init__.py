"""First-party text preprocessing: BPE tokenizer + the text→tensor
transform that makes the reference's text configs (SURVEY §6 configs
3/4 — IMDb LSTM, BERT fine-tune) runnable from RAW text instead of
pre-tokenized integers.

The reference has no tokenizer of its own — its text pipelines assume
the user ships preprocessing inside ``compile_code`` (reference:
microservices/binary_executor_image/binary_execution.py:246-268).
Here tokenization is a first-class transform: deterministic, stored
with the artifact, and emitting FIXED-LENGTH int32 rows — the static
shapes XLA needs (a ragged text batch cannot tile onto the MXU; a
(B, max_len) int32 block can).
"""

from learningorchestra_tpu.text.bpe import BpeTokenizer

__all__ = ["BpeTokenizer"]
