"""Runtime lock witness ("losan") — the dynamic half of the lochecks
concurrency model.

Every first-party ``threading.Lock``/``RLock``/``Condition`` in the
package is constructed through :func:`make_lock` / :func:`make_rlock` /
:func:`make_condition` with a NAME that matches the static analyzer's
lock identity (``Class.attr`` for instance locks, ``module.var`` for
module-level locks — the whole-program pass's ``lock-name-mismatch``
rule enforces the congruence).  With the witness OFF (the default) the
factories return plain ``threading`` primitives — zero wrapper, zero
hot-path cost.  With it ON (``LO_TPU_WITNESS=1`` at import, or
:func:`set_witness` before the objects under test are constructed)
locks come back instrumented and the witness records, per thread:

- **acquisition-order edges**: acquiring B while holding A is an A→B
  edge with the first observed call site — the OBSERVED lock-order
  graph that ``analysis/witness.py`` cross-checks against the static
  whole-program graph (a witnessed edge the static model lacks is a
  false negative in the model and fails the build);
- **held-while-blocking events**: a thread that already holds locks
  stalling on another lock's acquire (the contention shape behind
  every inversion deadlock), kept in a bounded ring;
- **holders and waiters** per lock, so the deadlock watchdog — and
  ``GET /observability/locks`` — can dump who owns what and who has
  been waiting how long, with live thread stacks.

The witness's own bookkeeping is guarded by ONE plain (un-witnessed)
module lock; instrumented ``acquire`` never blocks while holding it.

Env knobs (read directly, not via config.py — this module must import
before any config exists because config.py itself constructs a lock;
they are registered in ``config.DIRECT_ENV_KNOBS``):

- ``LO_TPU_WITNESS=1``       enable at import
- ``LO_TPU_WITNESS_STALL_S`` stall-watchdog threshold (default 30 s):
  a waiter blocked longer is logged with a full holder/waiter dump
- ``LO_TPU_WITNESS_DUMP``    path; when set (and the witness is on) a
  JSON snapshot is written at interpreter exit for
  ``scripts/lo_check.py --witness`` to cross-check offline
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import sys
import threading
import time
import traceback
import weakref
from collections import deque

__all__ = [
    "make_lock",
    "make_rlock",
    "make_condition",
    "witness_enabled",
    "set_witness",
    "snapshot",
    "reset",
]

_logger = logging.getLogger("learningorchestra_tpu.locks")

_THIS_FILE = __file__


def _stall_threshold_s() -> float:
    try:
        return float(os.environ.get("LO_TPU_WITNESS_STALL_S", "30"))
    except ValueError:
        return 30.0


# -- witness state (guarded by _STATE_LOCK; never witnessed) -----------------

_STATE_LOCK = threading.Lock()
_ENABLED = os.environ.get("LO_TPU_WITNESS", "").strip() == "1"
#: (held_name, acquired_name) -> {"count": int, "site": "file:line"}
_EDGES: dict = {}
_MAX_EDGES = 4096
#: bounded ring of held-while-blocking contention events
_EVENTS: deque = deque(maxlen=256)
#: live instrumented locks (weak — a dropped ReplicaSet's locks go too)
_LOCKS: "weakref.WeakSet" = weakref.WeakSet()
_TLS = threading.local()
_WATCHDOG: threading.Thread | None = None
#: The CURRENT watchdog's stop event — one per thread generation, so
#: a disable→enable flip can never revive a stopping thread (it owns
#: its own event; the replacement gets a fresh one).
_WATCHDOG_STOP: threading.Event | None = None
#: (lock_name, tid) pairs already stall-logged (log once per episode)
_STALLED_LOGGED: set = set()


def witness_enabled() -> bool:
    return _ENABLED


def set_witness(enabled: bool) -> None:
    """Flip the witness for locks constructed FROM NOW ON (existing
    plain locks stay plain — enable before building the objects under
    test; tests construct fresh engines/services per fixture).
    Disabling also stops the stall watchdog; the next witnessed lock
    construction restarts it."""
    global _ENABLED, _WATCHDOG
    _ENABLED = bool(enabled)
    if not _ENABLED:
        with _STATE_LOCK:
            if _WATCHDOG_STOP is not None:
                _WATCHDOG_STOP.set()
            _WATCHDOG = None


def make_lock(name: str):
    """A ``threading.Lock`` (plain when the witness is off, witnessed
    when on).  ``name`` must equal the static identity —
    ``Class.attr`` / ``module.var`` — so observed edges line up with
    the whole-program graph."""
    if not _ENABLED:
        return threading.Lock()
    return _WitnessLock(name, reentrant=False)


def make_rlock(name: str):
    """Reentrant variant of :func:`make_lock`."""
    if not _ENABLED:
        return threading.RLock()
    return _WitnessLock(name, reentrant=True)


def make_condition(name: str) -> threading.Condition:
    """A plain ``threading.Condition`` — named for the static model's
    benefit only.  Conditions are NOT witnessed: ``wait()`` releases
    and re-acquires the underlying lock out of band, which would
    corrupt the per-thread held stack; the static analyzer still
    models ``with self._cv:`` nesting."""
    del name
    return threading.Condition()


def _call_site() -> str:
    """First caller frame outside this module, as ``file:line``."""
    frame = sys._getframe(1)
    while frame is not None and frame.f_code.co_filename == _THIS_FILE:
        frame = frame.f_back
    if frame is None:
        return "<unknown>:0"
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"


def _held_stack() -> list:
    """The calling thread's held WITNESSED LOCK OBJECTS, in
    acquisition order.  Objects, not names: two instances of one class
    share a NAME (type-level identity), and release bookkeeping must
    not confuse sibling instances.

    Entries invalidated by a CROSS-THREAD release (legal for
    ``threading.Lock`` handoff patterns — release() on another thread
    cannot reach this thread's TLS) are pruned lazily: a lock this
    thread still held would still name it as owner."""
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    elif held:
        me = threading.get_ident()
        if any(lock._owner_tid != me for lock in held):
            held[:] = [
                lock for lock in held if lock._owner_tid == me
            ]
    return held


class _WitnessLock:
    """Witnessed Lock/RLock stand-in: same acquire/release/context-
    manager surface, with order/holder/waiter bookkeeping around the
    real primitive."""

    def __init__(self, name: str, *, reentrant: bool):
        self.name = name
        self.reentrant = reentrant
        self._inner = (
            threading.RLock() if reentrant else threading.Lock()
        )
        self._owner: str | None = None
        self._owner_tid: int | None = None
        #: tid -> (since_monotonic, thread_name); guarded by _STATE_LOCK
        self._waiters: dict = {}
        with _STATE_LOCK:
            _LOCKS.add(self)
            _ensure_watchdog_locked()

    # -- lock protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        thread = threading.current_thread()
        got = self._inner.acquire(False)
        if not got:
            if not blocking:
                return False
            self._note_waiting(thread)
            try:
                if timeout is not None and timeout >= 0:
                    got = self._inner.acquire(True, timeout)
                else:
                    got = self._inner.acquire()
            finally:
                self._clear_waiting(thread)
        if got:
            self._note_acquired(thread)
        return got

    def release(self) -> None:
        self._note_released()
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        if self.reentrant:
            return self._owner_tid is not None
        return self._inner.locked()

    # -- bookkeeping -----------------------------------------------------

    def _note_waiting(self, thread) -> None:
        held_names = [lock.name for lock in _held_stack()]
        with _STATE_LOCK:
            self._waiters[thread.ident] = (
                time.monotonic(), thread.name
            )
            if held_names:
                _EVENTS.append({
                    "held": list(dict.fromkeys(held_names)),
                    "wanted": self.name,
                    "thread": thread.name,
                    "site": _call_site(),
                    "at": time.time(),
                })
        if held_names:
            # Function-local import: obs.flight imports make_lock from
            # this module.  record() is lock-free, so this is safe even
            # though the caller is about to block on a witnessed lock.
            from learningorchestra_tpu.obs import flight as _flight
            _flight.record(
                "locks", "contention",
                wanted=self.name, thread=thread.name,
                held=list(dict.fromkeys(held_names)),
            )

    def _clear_waiting(self, thread) -> None:
        with _STATE_LOCK:
            self._waiters.pop(thread.ident, None)
            _STALLED_LOGGED.discard((self.name, thread.ident))

    def _note_acquired(self, thread) -> None:
        held = _held_stack()
        # Identity, not name: a reentrant re-acquire of THIS lock adds
        # no edges, but a sibling instance with the same type-level
        # name still records (the edge loop below skips the resulting
        # name self-edge).
        first = all(lock is not self for lock in held)
        if first:
            site = _call_site()
            with _STATE_LOCK:
                for h in dict.fromkeys(
                    lock.name for lock in held
                ):
                    if h == self.name:
                        continue
                    rec = _EDGES.get((h, self.name))
                    if rec is None:
                        if len(_EDGES) >= _MAX_EDGES:
                            continue
                        rec = _EDGES[(h, self.name)] = {
                            "count": 0, "site": site,
                        }
                    rec["count"] += 1
        held.append(self)
        self._owner = thread.name
        self._owner_tid = thread.ident

    def _note_released(self) -> None:
        held = getattr(_TLS, "held", [])
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        if all(lock is not self for lock in held):
            self._owner = None
            self._owner_tid = None


# -- stall watchdog ----------------------------------------------------------


def _ensure_watchdog_locked() -> None:
    """Start the stall watchdog lazily with the first witnessed lock
    (caller holds _STATE_LOCK)."""
    global _WATCHDOG, _WATCHDOG_STOP
    if _WATCHDOG is not None and _WATCHDOG.is_alive():
        return
    stop = threading.Event()
    _WATCHDOG_STOP = stop
    _WATCHDOG = threading.Thread(
        target=_watchdog_loop, args=(stop,),
        name="lo-lock-witness", daemon=True,
    )
    _WATCHDOG.start()


def _watchdog_loop(stop: threading.Event) -> None:
    while not stop.wait(1.0):
        stall_s = _stall_threshold_s()
        now = time.monotonic()
        dumps = []
        with _STATE_LOCK:
            for lock in list(_LOCKS):
                for tid, (since, tname) in lock._waiters.items():
                    key = (lock.name, tid)
                    if now - since > stall_s and key not in _STALLED_LOGGED:
                        _STALLED_LOGGED.add(key)
                        dumps.append((lock.name, tname, now - since,
                                      lock._owner))
        for name, waiter, for_s, owner in dumps:
            # Outside the state lock: formatting stacks is slow.
            _logger.error(
                "lock witness: %s has waited %.1fs for %s "
                "(holder: %s) — possible deadlock; "
                "GET /observability/locks for the full dump\n%s",
                waiter, for_s, name, owner or "<unheld>",
                _format_stacks(),
            )
            # A stall is exactly the moment the flight rings are worth
            # freezing: record the episode and ask for a debug bundle
            # (no-op unless a server has wired the bundle service).
            from learningorchestra_tpu.obs import bundle as _bundle
            from learningorchestra_tpu.obs import flight as _flight
            _flight.record(
                "locks", "stall",
                lock=name, thread=waiter,
                forS=round(for_s, 3), holder=owner or "",
            )
            _bundle.trigger(
                "lock_stall",
                lock=name, thread=waiter, forS=round(for_s, 3),
            )


def _format_stacks() -> str:
    frames = sys._current_frames()
    out = []
    for thread in threading.enumerate():
        frame = frames.get(thread.ident)
        if frame is None:
            continue
        out.append(f"--- {thread.name} ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


# -- snapshot / reset --------------------------------------------------------


def snapshot(include_stacks: bool = False) -> dict:
    """The witness's observed state: edges, contention events, and the
    currently held/contended locks with holders and waiters (plus
    their live stacks when ``include_stacks`` — the
    ``GET /observability/locks`` dump)."""
    now = time.monotonic()
    stall_s = _stall_threshold_s()
    with _STATE_LOCK:
        edges = [
            {"from": a, "to": b,
             "count": rec["count"], "site": rec["site"]}
            for (a, b), rec in sorted(_EDGES.items())
        ]
        events = list(_EVENTS)
        locks = []
        involved: set = set()
        registered = 0
        for lock in list(_LOCKS):
            registered += 1
            waiters = [
                {"thread": tname, "tid": tid,
                 "forS": round(now - since, 3)}
                for tid, (since, tname) in lock._waiters.items()
            ]
            if lock._owner is None and not waiters:
                continue
            if lock._owner_tid is not None:
                involved.add(lock._owner_tid)
            involved.update(w["tid"] for w in waiters)
            locks.append({
                "name": lock.name,
                "reentrant": lock.reentrant,
                "owner": lock._owner,
                "waiters": waiters,
            })
    stalls = [
        {"name": entry["name"], "waiter": w["thread"],
         "forS": w["forS"]}
        for entry in locks for w in entry["waiters"]
        if w["forS"] > stall_s
    ]
    doc = {
        "enabled": _ENABLED,
        "registeredLocks": registered,
        "stallThresholdS": stall_s,
        "edges": edges,
        "events": events,
        "locks": sorted(locks, key=lambda e: e["name"]),
        "stalls": stalls,
    }
    if include_stacks and involved:
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        doc["stacks"] = {
            names.get(tid, str(tid)): traceback.format_stack(
                frames[tid]
            )
            for tid in sorted(involved) if tid in frames
        }
    return doc


def reset() -> None:
    """Drop every recorded edge/event (tests isolate scenarios with
    this; live locks and their holder state are untouched)."""
    with _STATE_LOCK:
        _EDGES.clear()
        _EVENTS.clear()
        _STALLED_LOGGED.clear()


def _dump_at_exit() -> None:
    path = os.environ.get("LO_TPU_WITNESS_DUMP", "").strip()
    if not path or not _ENABLED:
        return
    try:
        with open(path, "w") as fh:
            json.dump(snapshot(), fh, indent=2, default=str)
    except OSError:  # noqa: PERF203 — best-effort at exit
        pass


atexit.register(_dump_at_exit)
