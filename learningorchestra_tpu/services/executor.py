"""Executor service: tune / train / evaluate / predict.

The reference's binaryExecutor (microservices/binary_executor_image/): load
the parent binary, ``getattr(instance, method)(**treated_params)``, persist
— train-family methods return the mutated instance itself
(binary_execution.py:188-200); other methods' results are stored as result
rows + binary.  The lineage walk finds the original model spec behind any
chain of steps (utils.py:261-280).

Tune adds what the reference leaves to the user: a managed grid-search
(``param_grid``) that fits one candidate per combination and records each
candidate's score as a result row, selecting the best instance.
"""

from __future__ import annotations

import contextlib
import itertools
import shutil
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Any

import numpy as np

from learningorchestra_tpu import dsl
from learningorchestra_tpu.train.neural import NeuralEstimator
from learningorchestra_tpu.services.context import (
    ServiceContext,
    ValidationError,
)
from learningorchestra_tpu.toolkit import registry

TRAIN_KINDS = ("train", "tune")


def store_history_rows(documents, name: str, history: dict) -> int:
    """Persist a TrainHistory-shaped dict ({metric: [per-epoch...]}) as one
    pollable row per epoch — the durable metrics contract (SURVEY §5.5).
    Shared by the single-device and distributed train paths."""
    keys = list(history)
    n = max((len(history[k]) for k in keys), default=0)
    for i in range(n):
        documents.insert_one(
            name,
            {
                "docType": "history",
                "epoch": i,
                **{
                    k: history[k][i] for k in keys if len(history[k]) > i
                },
            },
        )
    return n


class ExecutorService:
    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    # -- shared validation (reference: server.py:332-398) ---------------------

    @staticmethod
    def _reject_raw_checkpoint_dir(method_parameters) -> None:
        """Checkpoint placement is managed server-side (ctx.checkpoint_dir);
        a raw path from the network would be written/pruned verbatim."""
        if method_parameters and "checkpoint_dir" in method_parameters:
            raise ValidationError(
                "checkpoint_dir is managed by the service; use "
                "checkpoint_every/resume to control checkpointing"
            )

    def _validate_request(self, name, parent_name, method, method_parameters):
        self.ctx.require_new_name(name)
        self._reject_raw_checkpoint_dir(method_parameters)
        parent_meta = self.ctx.require_finished_parent(parent_name)
        model_meta = self.ctx.artifacts.metadata.find_model_ancestor(
            parent_name
        )
        factory = registry.resolve(
            model_meta.get("modulePath"), model_meta.get("class")
        )
        if not registry.validate_method(factory, method):
            raise ValidationError(f"no such method: {method!r}")
        bad = registry.validate_method_params(
            factory, method, method_parameters or {}
        )
        if bad:
            raise ValidationError(f"invalid methodParameters: {bad}")
        return parent_meta, model_meta

    # -- create ---------------------------------------------------------------

    def create(
        self,
        name: str,
        *,
        parent_name: str,
        method: str,
        method_parameters: dict | None = None,
        artifact_type: str = "train/tensorflow",
        description: str = "",
        deadline_s: float | None = None,
    ) -> dict:
        parent_meta, model_meta = self._validate_request(
            name, parent_name, method, method_parameters
        )
        meta = self.ctx.artifacts.metadata.create(
            name,
            artifact_type,
            parent_name=parent_name,
            module_path=model_meta.get("modulePath"),
            class_name=model_meta.get("class"),
            method=method,
        )
        self._submit(
            name, parent_meta, method, method_parameters, artifact_type,
            description, resume_checkpoint=False,
            warm_key=_warm_key(model_meta, method, method_parameters),
            deadline_s=deadline_s,
        )
        return meta

    def update(
        self,
        name: str,
        *,
        method_parameters: dict | None = None,
        description: str = "",
        deadline_s: float | None = None,
    ) -> dict:
        """PATCH re-run with new parameters (reference:
        server.py:110-156).

        A re-run of a FAILED train job resumes from its newest managed
        checkpoint (the preemption path); a re-run of a finished job is
        a fresh fit from epoch 0 — new parameters must actually apply,
        so stale checkpoints are cleared.
        """
        meta = self.ctx.require_existing(name)
        self._reject_raw_checkpoint_dir(method_parameters)
        parent = meta.get("parentName")
        if not parent:
            raise ValidationError(
                f"artifact {name!r} has no parent — not an executor result"
            )
        parent_meta = self.ctx.require_finished_parent(parent)
        resume = meta.get("jobState") == "failed"
        if not method_parameters:
            # Bare PATCH ("just resume"): fall back to the original
            # request's parameters from the execution ledger (ADVICE r1).
            method_parameters = self.ctx.last_recorded_parameters(name)
        self.ctx.artifacts.metadata.restart(name)
        self._submit(
            name, parent_meta, meta.get("method"), method_parameters,
            meta.get("type"), description, resume_checkpoint=resume,
            warm_key=_warm_key(
                meta, meta.get("method"), method_parameters
            ),
            deadline_s=deadline_s,
        )
        return self.ctx.artifacts.metadata.read(name)

    def _submit(self, name, parent_meta, method, method_parameters,
                artifact_type, description, *, resume_checkpoint=False,
                warm_key=None, deadline_s=None):
        parent_name = parent_meta["name"]
        parent_type = parent_meta.get("type", "")
        kind = artifact_type.split("/", 1)[0]

        def run():
            from learningorchestra_tpu.jobs import engine as engine_mod
            from learningorchestra_tpu.obs import costs as obs_costs
            from learningorchestra_tpu.obs import tracing as obs_tracing
            from learningorchestra_tpu.train import compile_cache

            cache_before = compile_cache.counters_snapshot()
            with obs_tracing.span("load_artifact", parent=parent_name):
                instance = self.ctx.volumes.read_object(
                    parent_type, parent_name
                )
            params = dsl.resolve_params(method_parameters, self.ctx.loader)
            # Which preemption-retry attempt is this body running as?
            # 0 on the first execution; >0 means the engine's in-loop
            # retry re-invoked us after a ``Preempted`` — resume from
            # the managed checkpoints THIS run already wrote instead
            # of restarting at epoch 0 (previously only a manual PATCH
            # of a failed job got resume semantics).
            attempt = engine_mod.current_attempt()
            resume = resume_checkpoint or attempt > 0
            if (
                kind in TRAIN_KINDS
                and method == "fit"
                and getattr(
                    instance, "supports_managed_checkpoints", False
                )
                and "checkpoint_dir" not in params
            ):
                # Managed in-loop checkpointing: a FAILED train job
                # PATCHed back resumes from its newest checkpoint instead
                # of epoch 0 (train/checkpoint.py; the reference loses
                # mid-job state entirely, SURVEY §5.4).  Fresh runs and
                # param-changing re-runs of finished jobs must not
                # resurrect old state, so their checkpoint dir is wiped
                # — but only on attempt 0: a retry's checkpoints are
                # its own run's state, never stale.
                ckdir = self.ctx.checkpoint_dir(name)
                if not resume and ckdir.exists():
                    shutil.rmtree(ckdir, ignore_errors=True)
                params["checkpoint_dir"] = str(ckdir)
                params.setdefault("resume", resume)
                if attempt > 0:
                    # A caller-specified resume=False means "fresh
                    # fit", which attempt 0 honored (the wipe above
                    # didn't run on retries); resuming the SAME
                    # logical run's checkpoints after preemption is
                    # still that fresh fit, continued.
                    params["resume"] = True
            t0 = time.perf_counter()
            # Device-time attribution scope (obs/costs.py): dispatches
            # the body makes (the fit epoch loop) book against THIS
            # job's ledger entry.
            with obs_costs.job_scope(name):
                if isinstance(instance, NeuralEstimator):
                    # On-device work: take a chip lease so concurrent
                    # neural jobs get placed, not interleaved
                    # (jobs/leases.py).
                    with self.ctx.leaser.lease(1, label=name) as devs:
                        if devs:
                            self.ctx.artifacts.metadata.update(
                                name, {"leasedDevices": devs}
                            )
                        result = getattr(instance, method)(**params)
                else:
                    result = getattr(instance, method)(**params)
            fit_time = time.perf_counter() - t0
            if isinstance(instance, NeuralEstimator) and \
                    compile_cache.enabled():
                # The job's compiled programs are now cached: publish
                # the warm hint (the dispatcher prefers queued
                # same-program jobs) and the per-job counter delta —
                # cache effectiveness observable from the ordinary
                # GET/poll path.  Counters are process-wide, so under
                # concurrent jobs the delta is an upper bound.  With
                # the cache disabled nothing is ever warm — a hint
                # would reorder the queue for zero benefit.
                self.ctx.engine.note_warm(warm_key)
            cache_delta = compile_cache.delta_since(cache_before)
            # Epoch fence at publication: a stale-epoch straggler (a
            # pre-crash worker racing a recovered orchestrator) must
            # not overwrite the artifact a newer epoch owns.
            self.ctx.require_current_epoch()
            if kind in TRAIN_KINDS or result is instance:
                # Train semantics: persist the mutated instance
                # (binary_execution.py:195-200).
                self.ctx.volumes.save_object(artifact_type, name, instance)
                # A PATCH re-train just replaced this artifact's binary:
                # a serving registry holding its old params resident
                # must reload before the next request.
                self.ctx.notify_artifact_changed(name)
                extra = {"fitTime": fit_time,
                         "compileCache": cache_delta}
                device_time = obs_costs.job_summary(name)
                if device_time is not None:
                    # Attributed device seconds/flops (and MFU when a
                    # peak is configured) — cost accounting observable
                    # from the ordinary GET/poll path.
                    extra["deviceTime"] = device_time
                hist = getattr(instance, "history", None)
                if hist:
                    # Re-runs re-store the full history; drop the old
                    # rows or epochs would duplicate.
                    for doc in self.ctx.documents.find(
                        name, query={"docType": "history"}
                    ):
                        self.ctx.documents.delete_one(name, doc["_id"])
                    store_history_rows(self.ctx.documents, name, hist)
                return extra
            # Evaluate/predict semantics: persist result rows + binary.
            self.ctx.volumes.save_object(artifact_type, name, result)
            self._store_result_rows(name, result)
            return {"fitTime": fit_time}

        self.ctx.engine.submit(
            name,
            run,
            description=description or f"{method} on {parent_name}",
            method=method,
            parameters=_json_safe(method_parameters),
            on_success=lambda extra: extra,
            job_class="executor",
            warm_key=warm_key,
            deadline_s=deadline_s,
        )

    def _store_result_rows(self, name: str, result: Any) -> None:
        """Make method results pollable as rows (the reference stores
        results in the collection for GET; utils.py:116-139)."""
        if isinstance(result, dict):
            self.ctx.documents.insert_one(name, _json_safe(result))
            return
        arr = np.asarray(result)
        if arr.ndim == 0:
            self.ctx.documents.insert_one(name, {"result": arr.item()})
        elif arr.ndim == 1:
            self.ctx.documents.insert_many(
                name, ({"result": _json_safe(v)} for v in arr.tolist())
            )
        else:
            self.ctx.documents.insert_many(
                name, ({"result": row} for row in arr.tolist())
            )

    # -- tune: managed grid search -------------------------------------------

    def create_tune(
        self,
        name: str,
        *,
        parent_name: str,
        method: str = "fit",
        param_grid: dict | None = None,
        method_parameters: dict | None = None,
        scoring_parameters: dict | None = None,
        artifact_type: str = "tune/tensorflow",
        description: str = "",
        deadline_s: float | None = None,
    ) -> dict:
        """Grid-search over ``param_grid`` (dict of lists).  Each candidate
        re-instantiates the model ancestor's class with those kwargs, fits
        with ``method_parameters``, scores with ``score``/``evaluate`` on
        ``scoring_parameters`` (defaults to the fit data), and the best
        candidate instance is persisted as this artifact's binary."""
        if not param_grid:
            raise ValidationError("param_grid is required for tune")
        for key, values in param_grid.items():
            if not isinstance(values, (list, tuple)) or not values:
                raise ValidationError(
                    f"param_grid[{key!r}] must be a non-empty list"
                )
        self.ctx.require_new_name(name)
        self._reject_raw_checkpoint_dir(method_parameters)
        self.ctx.require_finished_parent(parent_name)
        model_meta = self.ctx.artifacts.metadata.find_model_ancestor(
            parent_name
        )
        factory = registry.resolve(
            model_meta.get("modulePath"), model_meta.get("class")
        )
        bad = registry.validate_init_params(
            model_meta.get("modulePath"), model_meta.get("class"),
            {k: None for k in param_grid},
        )
        if bad:
            raise ValidationError(f"param_grid keys not in __init__: {bad}")
        meta = self.ctx.artifacts.metadata.create(
            name,
            artifact_type,
            parent_name=parent_name,
            module_path=model_meta.get("modulePath"),
            class_name=model_meta.get("class"),
            method=method,
        )

        warm_key = _warm_key(model_meta, method, param_grid)

        def run():
            from learningorchestra_tpu.jobs import engine as engine_mod
            from learningorchestra_tpu.train import compile_cache

            cache_before = compile_cache.counters_snapshot()
            # Preemption-retry resume for the TRIALS (the PR-7
            # current_attempt() threading, mirroring the single-fit
            # path): each neural trial owns a managed checkpoint dir
            # keyed by its stable combo index, so a retry of the grid
            # resumes every trial from its newest checkpoint instead
            # of epoch 0.  Attempt 0 wipes the tree — a fresh grid
            # must not resurrect a previous run's trial state.
            attempt = engine_mod.current_attempt()
            trial_ck_root = self.ctx.checkpoint_dir(name)
            if attempt == 0 and trial_ck_root.exists():
                shutil.rmtree(trial_ck_root, ignore_errors=True)
            fit_params = dsl.resolve_params(
                method_parameters, self.ctx.loader
            )
            score_params = dsl.resolve_params(
                scoring_parameters, self.ctx.loader
            ) if scoring_parameters else {
                k: v for k, v in fit_params.items() if k in ("x", "y")
            }
            keys = sorted(param_grid)
            combos = [
                dict(zip(keys, combo))
                for combo in itertools.product(
                    *(param_grid[k] for k in keys)
                )
            ]

            def eval_candidate(idx: int, kwargs: dict):
                from learningorchestra_tpu.jobs.leases import (
                    jax_device_for,
                )
                from learningorchestra_tpu.obs import (
                    costs as obs_costs,
                )

                candidate = factory(**kwargs)
                trial_params = fit_params
                if (
                    isinstance(candidate, NeuralEstimator)
                    and method == "fit"
                ):
                    # Managed per-trial checkpoints: combos is built
                    # deterministically (sorted keys x product), so
                    # index idx names the same trial on every retry.
                    trial_params = dict(fit_params)
                    trial_params.setdefault(
                        "checkpoint_dir",
                        str(trial_ck_root / f"trial_{idx:04d}"),
                    )
                    trial_params.setdefault("resume", attempt > 0)
                if isinstance(candidate, NeuralEstimator):
                    # Each trial leases a chip for its on-device work
                    # (VERDICT r1 weak item 4; reference parity: Ray
                    # placement groups, server.py:16) — and RUNS there:
                    # on a multi-chip host, trials spread ACROSS the
                    # chips concurrently, each pinned to its lease via
                    # jax.default_device (BASELINE config 4's
                    # grid-search-over-a-slice shape).  Single chip
                    # degenerates to the serialized round 2 behavior.
                    lease = self.ctx.leaser.lease(
                        1, label=f"{name}:trial"
                    )
                else:
                    lease = contextlib.nullcontext([])
                with lease as devs:
                    import jax

                    dev = jax_device_for(devs[0]) if devs else None
                    place = jax.default_device(dev) \
                        if dev is not None else contextlib.nullcontext()
                    # Re-bind the job scope: trials run on pool
                    # threads, which do not inherit the engine
                    # thread's context — every candidate's epochs
                    # still book against THIS tune job.
                    with place, obs_costs.job_scope(name):
                        t0 = time.perf_counter()
                        getattr(candidate, method)(**trial_params)
                        fit_time = time.perf_counter() - t0
                        score = float(candidate.score(**score_params))
                return candidate, score, fit_time

            # Candidates run concurrently (the reference trains its
            # builder classifiers in parallel threads the same way,
            # builder_image/builder.py:62-78); device compute serializes
            # on the accelerator, but host-side prep/score overlap.
            # Trials stream: each result doc inserts as it completes
            # (clients polling GET see progress) and only the current
            # best candidate's parameters stay referenced — a big grid
            # over a large model must not hold every fitted candidate.
            best_score, best_instance, best_combo = -np.inf, None, None
            # Worker pool sizes to the CHIP pool only when trials
            # actually lease chips (the v4-8 shape runs 8 neural trials
            # at once, one per chip); host-only grids keep the bounded
            # 4-thread default — they never lease, so chip-count
            # threads would just oversubscribe host CPU/RAM.
            trials_lease = isinstance(factory, type) and issubclass(
                factory, NeuralEstimator
            )
            n_chips = self.ctx.leaser.device_count if trials_lease else 0
            workers = min(len(combos), max(4, n_chips))
            with ThreadPoolExecutor(max_workers=workers) as pool:
                futures = {
                    pool.submit(eval_candidate, i, kw): kw
                    for i, kw in enumerate(combos)
                }
                try:
                    for fut in as_completed(list(futures)):
                        # pop: a consumed future (and its non-best
                        # candidate) must become collectable now, not
                        # when the pool exits.
                        kwargs = futures.pop(fut)
                        candidate, score, fit_time = fut.result()
                        self.ctx.documents.insert_one(
                            name,
                            {
                                "params": _json_safe(kwargs),
                                "score": score,
                                "fitTime": fit_time,
                            },
                        )
                        if score > best_score:
                            best_score, best_instance, best_combo = (
                                score, candidate, kwargs,
                            )
                except Exception:
                    # First failure aborts the search: don't burn the
                    # accelerator fitting the remaining queued combos.
                    for pending in futures:
                        pending.cancel()
                    raise
            self.ctx.require_current_epoch()
            self.ctx.volumes.save_object(artifact_type, name, best_instance)
            self.ctx.notify_artifact_changed(name)
            # Trial checkpoints are per-run scratch: the grid is done,
            # the best candidate is published — keeping them would only
            # let a FUTURE unrelated grid resurrect stale trial state.
            shutil.rmtree(trial_ck_root, ignore_errors=True)
            if trials_lease and compile_cache.enabled():
                self.ctx.engine.note_warm(warm_key)
            # Grid-level compile-cache accounting: candidates sharing
            # an architecture coalesce onto ONE trace (the rest hit),
            # so for an N-candidate same-arch sweep expect hits ≈ N-1
            # per program kind.  Concurrent unrelated jobs can inflate
            # the delta (process-wide counters).
            out = {
                "bestScore": best_score,
                "bestParams": _json_safe(best_combo),
                "compileCache": compile_cache.delta_since(cache_before),
            }
            from learningorchestra_tpu.obs import costs as obs_costs

            device_time = obs_costs.job_summary(name)
            if device_time is not None:
                out["deviceTime"] = device_time
            return out

        self.ctx.engine.submit(
            name, run, description=description or f"grid search {parent_name}",
            method=method, parameters=_json_safe(param_grid),
            on_success=lambda extra: extra,
            job_class="executor",
            warm_key=warm_key,
            deadline_s=deadline_s,
        )
        return meta

    def delete(self, name: str) -> None:
        self.ctx.delete_artifact(name)


def _warm_key(meta: dict, method,
              method_parameters: dict | None = None) -> str | None:
    """Program-fingerprint warm hint for the engine's warm-start
    dispatch preference (``compile_cache.warm_fingerprint``): the
    submitted spec's trace-shaping parameters hash into the key, so
    two jobs share a hint exactly when they would very likely share
    traced programs — an optimizer or layer-width change separates
    them, where the old coarse ``module:class:method`` tag lumped a
    whole class together.  A HINT, not a guarantee — exact matching
    happens inside compile_cache; a wrong hint merely reorders one
    class's queue."""
    from learningorchestra_tpu.train import compile_cache

    module_path = meta.get("modulePath")
    class_name = meta.get("class")
    if not module_path or not class_name:
        return None
    return compile_cache.warm_fingerprint(
        module_path, class_name, method, method_parameters
    )


def _json_safe(obj):
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)
