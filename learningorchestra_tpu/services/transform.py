"""Transform service: projection, dtype casting, generic transform executor.

Reference parity:
- **projection** — column-select a dataset into a new collection; the
  reference runs this as a Spark job through the mongo-spark connector
  (microservices/projection_image/projection.py:20-48).  A column
  projection over a document store needs no cluster: here it is a
  batched host-side copy (and numeric transforms go through the JAX
  estimators instead).
- **dataType** — cast dataset fields string↔number in place, re-flagging
  the artifact unfinished while the cast runs
  (data_type_handler_image/data_type_update.py:15-59).
- **generic transform** — instantiate a registry class, call a method with
  DSL-treated params, persist the result binary
  (database_executor_image/database_execution.py:92-188).
"""

from __future__ import annotations

from learningorchestra_tpu import dsl
from learningorchestra_tpu.services.context import (
    ServiceContext,
    ValidationError,
)
from learningorchestra_tpu.toolkit import registry

PROJECTION_TYPE = "transform/projection"
TEXT_TYPE = "transform/text"


def _tokenizer_volume_name(artifact_name: str) -> str:
    """The trained tokenizer binary sits NEXT to the artifact's shard
    directory in the transform volume (every transform/* type maps to
    one volume key — store/volumes.py::volume_key_for_type), so it
    needs a distinct name; '.' cannot appear in a path traversal and
    is valid for volume names."""
    return artifact_name + ".tokenizer"


def _compact_best_effort(documents, name: str) -> None:
    """WAL compaction is maintenance, never the job's outcome: a failed
    rewrite (transient disk/permission issue) must not fail a job whose
    actual work already committed."""
    if not hasattr(documents, "compact"):
        return
    try:
        documents.compact(name)
    except Exception as exc:  # noqa: BLE001 — maintenance only
        from learningorchestra_tpu.log import get_logger

        get_logger("store").warning(
            "compact(%s) failed (ignored): %r", name, exc
        )


class TransformService:
    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    # -- projection -----------------------------------------------------------

    def create_projection(
        self, name: str, parent_name: str, fields: list[str]
    ) -> dict:
        parent = self.ctx.require_finished_parent(parent_name)
        self.ctx.require_new_name(name)
        parent_fields = parent.get("fields") or []
        if parent_fields:
            missing = [f for f in fields if f not in parent_fields]
            if missing:
                raise ValidationError(
                    f"fields not in parent dataset: {missing}"
                )
        meta = self.ctx.artifacts.metadata.create(
            name, PROJECTION_TYPE, parent_name=parent_name,
            extra={"fields": fields},
        )
        self._submit_projection(name, parent_name, fields, replace=False)
        return meta

    def update_projection(
        self, name: str, fields: list[str] | None = None
    ) -> dict:
        """PATCH re-run (reference: PATCH /transform/projection →
        database_executor_image/server.py:91-148 — flip ``finished``
        False and re-execute): replaces the projected rows, with new
        ``fields`` when given, else the original request's."""
        meta = self.ctx.require_not_running(name)
        if meta.get("type") != PROJECTION_TYPE:
            raise ValidationError(f"{name!r} is not a projection")
        parent_name = meta.get("parentName")
        parent = self.ctx.require_finished_parent(parent_name)
        fields = fields or meta.get("fields") or []
        parent_fields = parent.get("fields") or []
        if parent_fields:
            missing = [f for f in fields if f not in parent_fields]
            if missing:
                raise ValidationError(
                    f"fields not in parent dataset: {missing}"
                )
        self.ctx.artifacts.metadata.restart(name)
        self._submit_projection(name, parent_name, fields, replace=True)
        return self.ctx.artifacts.metadata.read(name)

    def _submit_projection(
        self, name: str, parent_name: str, fields: list[str], *,
        replace: bool,
    ) -> None:
        def project():
            if replace:
                for doc in self.ctx.documents.find(
                    name,
                    query={
                        "_id": {"$gte": 1},
                        "docType": {"$ne": "execution"},
                    },
                ):
                    self.ctx.documents.delete_one(name, doc["_id"])
            if hasattr(self.ctx.documents, "project"):
                # Native scan: rows never materialize as Python objects
                # (the reference runs this as a Spark job over the
                # mongo connector; projection_image/projection.py:20-48).
                n = self.ctx.documents.project(parent_name, name, fields)
                if replace:
                    _compact_best_effort(self.ctx.documents, name)
                return {"rows": n, "fields": fields}
            docs = self.ctx.documents.find(
                parent_name,
                query={"_id": {"$gte": 1}, "docType": {"$ne": "execution"}},
            )
            out = (
                {f: d.get(f) for f in fields} for d in docs
            )
            n = self.ctx.documents.insert_many(name, out)
            if replace:
                # A replace wrote delete+insert WAL entries for every
                # row; fold the log back to current state.
                _compact_best_effort(self.ctx.documents, name)
            return {"rows": n, "fields": fields}

        self.ctx.engine.submit(
            name, project, description=f"projection of {parent_name}",
            parameters={"fields": fields},
            on_success=lambda r: r,
            job_class="transform",
        )

    # -- dtype casting --------------------------------------------------------

    def update_field_types(self, parent_name: str, fields: dict) -> dict:
        """Cast fields in place; value ∈ {"number", "string"} per field
        (reference: data_type_handler_image/utils.py:87-102)."""
        meta = self.ctx.require_existing(parent_name)
        known = meta.get("fields") or []
        for field, kind in fields.items():
            if kind not in ("number", "string"):
                raise ValidationError(
                    f"field {field!r}: type must be 'number' or 'string'"
                )
            if known and field not in known:
                raise ValidationError(f"no such field: {field!r}")
        # Re-flag unfinished while the cast runs (reference:
        # data_type_update.py:47-59), then restore.
        self.ctx.artifacts.metadata.restart(parent_name)

        def cast():
            n_updates = 0
            docs = self.ctx.documents.find(
                parent_name,
                query={"_id": {"$gte": 1}, "docType": {"$ne": "execution"}},
            )
            for doc in docs:
                updates = {}
                for field, kind in fields.items():
                    val = doc.get(field)
                    if val is None:
                        continue
                    if kind == "number":
                        try:
                            updates[field] = float(val)
                        except (TypeError, ValueError):
                            updates[field] = None
                    else:
                        updates[field] = str(val)
                if updates:
                    self.ctx.documents.update_one(
                        parent_name, doc["_id"], updates
                    )
                    n_updates += 1
            if n_updates:
                # The cast appended one update entry per document; fold
                # the WAL back to current state.
                _compact_best_effort(self.ctx.documents, parent_name)
            return {"cast": list(fields)}

        self.ctx.engine.submit(
            parent_name, cast, description=f"dtype cast {fields}",
            on_success=lambda r: r,
            job_class="transform",
        )
        return self.ctx.artifacts.metadata.read(parent_name)

    # -- text tokenization (BPE → tensor-sharded int rows) --------------------

    def create_text(
        self,
        name: str,
        parent_name: str,
        *,
        text_field: str,
        label_field: str | None = None,
        vocab_size: int = 8000,
        max_len: int = 128,
        lowercase: bool = True,
        tokenizer_from: str | None = None,
        shard_rows: int = 4096,
    ) -> dict:
        """Tokenize a text column into a tensor-sharded dataset of
        fixed-length int32 rows (+ integer labels) that the streaming
        fit surfaces consume directly (``x="$name"``,
        ``y="$name.label"``).

        The reference has no tokenizer service — its text configs
        assume user-shipped preprocessing in ``compile_code``
        (binary_executor_image/binary_execution.py:246-268).  Making it
        a transform keeps the whole text pipeline inside the framework:
        raw CSV → BPE → static-shape tensors (the XLA-friendly text
        representation) → train.  ``tokenizerFrom`` re-uses another
        text transform's trained tokenizer, the held-out-split
        contract (encode test data with the TRAIN split's vocab).
        """
        parent = self.ctx.require_finished_parent(parent_name)
        self.ctx.require_new_name(name)
        if not text_field:
            raise ValidationError("textField is required")

        def _int(value, key):
            # Malformed request input must be a 406, not an int() 500 —
            # and a non-integral float must not silently truncate.
            try:
                out = int(value)
                if isinstance(value, float) and value != out:
                    raise ValueError
                return out
            except (TypeError, ValueError):
                raise ValidationError(
                    f"{key} must be an integer, got {value!r}"
                ) from None

        vocab_size = _int(vocab_size, "vocabSize")
        max_len = _int(max_len, "maxLen")
        shard_rows = _int(shard_rows, "shardRows")
        if vocab_size < 8:
            raise ValidationError(f"vocabSize too small: {vocab_size}")
        if max_len < 4:
            raise ValidationError(f"maxLen too small: {max_len}")
        if shard_rows <= 0:
            raise ValidationError("shardRows must be positive")
        self._check_text_parent(parent, text_field, label_field)
        self._check_tokenizer_from(tokenizer_from)
        meta = self.ctx.artifacts.metadata.create(
            name, TEXT_TYPE, parent_name=parent_name,
            extra={
                "textField": text_field, "labelField": label_field,
                "vocabSize": int(vocab_size), "maxLen": int(max_len),
                "lowercase": bool(lowercase),
                "tokenizerFrom": tokenizer_from,
                "shardRows": int(shard_rows),
            },
        )
        self._submit_text(name, meta, replace=False)
        return meta

    def _check_tokenizer_from(self, tokenizer_from) -> None:
        """Malformed or dangling tokenizerFrom must be a 406 — never a
        volume-layer ValueError (500) or a job-time FileNotFoundError."""
        if tokenizer_from is None:
            return
        if not isinstance(tokenizer_from, str) or not tokenizer_from:
            raise ValidationError(
                f"tokenizerFrom must be an artifact name, "
                f"got {tokenizer_from!r}"
            )
        try:
            ok = self.ctx.volumes.exists(
                TEXT_TYPE, _tokenizer_volume_name(tokenizer_from)
            )
        except ValueError:
            raise ValidationError(
                f"invalid tokenizerFrom name: {tokenizer_from!r}"
            ) from None
        if not ok:
            raise ValidationError(
                f"no trained tokenizer named {tokenizer_from!r}"
            )

    @staticmethod
    def _check_text_parent(parent: dict, text_field: str,
                           label_field: str | None) -> None:
        """Shared by create AND PATCH re-run — the parent's schema may
        have changed between them (re-ingest with renamed columns), and
        a stale field name must be a 406, not an all-empty dataset."""
        if parent.get("sharded"):
            raise ValidationError(
                "text tokenization reads a document dataset (sharded "
                "datasets hold numeric columns only)"
            )
        known = parent.get("fields") or []
        for f in filter(None, (text_field, label_field)):
            if known and f not in known:
                raise ValidationError(f"no such field: {f!r}")

    def update_text(self, name: str) -> dict:
        """PATCH re-run: re-tokenizes from the parent's CURRENT rows
        with the original request's parameters (same contract as the
        projection PATCH)."""
        meta = self.ctx.require_not_running(name)
        if meta.get("type") != TEXT_TYPE:
            raise ValidationError(f"{name!r} is not a text transform")
        parent = self.ctx.require_finished_parent(meta.get("parentName"))
        self._check_text_parent(
            parent, meta.get("textField"), meta.get("labelField")
        )
        self._check_tokenizer_from(meta.get("tokenizerFrom"))
        self.ctx.artifacts.metadata.restart(name)
        self._submit_text(name, meta, replace=True)
        return self.ctx.artifacts.metadata.read(name)

    def _submit_text(self, name: str, meta: dict, *, replace: bool) -> None:
        parent_name = meta["parentName"]
        text_field = meta["textField"]
        label_field = meta.get("labelField")
        tokenizer_from = meta.get("tokenizerFrom")
        max_len = int(meta["maxLen"])

        def tokenize():
            import numpy as np

            from learningorchestra_tpu.services.dataset import (
                DatasetService,
            )
            from learningorchestra_tpu.store.sharded import (
                ShardedTensorWriter,
            )
            from learningorchestra_tpu.text import BpeTokenizer
            from learningorchestra_tpu.text.bpe import count_words

            docs = self.ctx.documents.find(
                parent_name,
                query={"_id": {"$gte": 1}, "docType": {"$ne": "execution"}},
            )
            if not docs:
                raise ValueError(f"dataset {parent_name!r} has no rows")

            classes: list | None = None
            labels = None
            if label_field is not None:
                import math

                raw = [d.get(label_field) for d in docs]
                n_missing = sum(
                    1 for v in raw
                    if v is None
                    or (isinstance(v, float) and not math.isfinite(v))
                )
                if n_missing:
                    # A missing/NaN label must be an error, not a
                    # phantom "None" class silently shifting every
                    # class id (or an int(NaN) crash).
                    raise ValueError(
                        f"{n_missing} row(s) have no "
                        f"{label_field!r} value; clean or project "
                        "the dataset first"
                    )
                if all(
                    isinstance(v, (int, float))
                    and float(v) == int(v) for v in raw
                ):
                    ints = [int(v) for v in raw]
                    uniq = sorted(set(ints))
                    if uniq == list(range(len(uniq))):
                        # Already dense [0, K) — store as-is.
                        labels = np.asarray(ints, np.int64)
                    else:
                        # Sparse/negative integer classes ({-1,1},
                        # {1,2}, ...): remap densely like strings —
                        # out-of-range ids silently corrupt the
                        # downstream one-hot (XLA clamps indices).
                        lut = {c: i for i, c in enumerate(uniq)}
                        labels = np.asarray(
                            [lut[v] for v in ints], np.int64
                        )
                        classes = [str(c) for c in uniq]
                else:
                    # String / non-integral labels: deterministic
                    # class ids (sorted order), recorded for decode.
                    classes = sorted({str(v) for v in raw})
                    lut = {c: i for i, c in enumerate(classes)}
                    labels = np.asarray(
                        [lut[str(v)] for v in raw], np.int64
                    )

            # Tokenizer work comes AFTER label validation: training is
            # the expensive step, and saving the trained tokenizer
            # before a validation failure would publish a live,
            # tokenizerFrom-reachable artifact from a FAILED job.
            if tokenizer_from:
                try:
                    tok = self.ctx.volumes.read_object(
                        TEXT_TYPE, _tokenizer_volume_name(tokenizer_from)
                    )
                except FileNotFoundError:
                    # Validated at request time, but a DELETE can land
                    # between queueing and running — surface it as a
                    # clear job error, not a raw traceback.
                    raise ValueError(
                        f"tokenizer {tokenizer_from!r} was deleted "
                        "before this job ran"
                    ) from None
            else:
                wc = count_words(
                    (d.get(text_field) or "" for d in docs),
                    lowercase=bool(meta.get("lowercase", True)),
                )
                tok = BpeTokenizer.train(
                    wc, vocab_size=int(meta["vocabSize"]),
                    lowercase=bool(meta.get("lowercase", True)),
                )
                # NOT saved yet: publish only after the shard writer
                # succeeds, so a failed run can't leave a live (or, on
                # PATCH, overwrite the previous good) tokenizer.

            root = self.ctx.volumes.path_for(TEXT_TYPE, name)
            if replace:
                if root.exists():
                    import shutil

                    shutil.rmtree(root)
                # Stale preview docs from the previous run too.
                for doc in self.ctx.documents.find(
                    name,
                    query={
                        "_id": {"$gte": 1},
                        "docType": {"$ne": "execution"},
                    },
                ):
                    self.ctx.documents.delete_one(name, doc["_id"])
            columns = {"tokens": (max_len,)}
            if labels is not None:
                columns["label"] = ()
            writer = ShardedTensorWriter(
                root, columns, rows_per_shard=int(meta["shardRows"]),
            )
            preview: list[dict] = []
            step = 1024
            for i in range(0, len(docs), step):
                enc = tok.encode_batch(
                    [d.get(text_field) or "" for d in docs[i:i + step]],
                    max_len,
                )
                chunk = {"tokens": enc}
                if labels is not None:
                    chunk["label"] = labels[i:i + step]
                writer.append_rows(chunk)
                # First rows also land in the document store so the
                # artifact's GET pages show data (sharded-CSV preview
                # parity — dataset.py PREVIEW_ROWS); token rows are
                # small, unlike image tensors, so previews are cheap.
                for j in range(len(enc)):
                    if len(preview) >= DatasetService.PREVIEW_ROWS:
                        break
                    row = {
                        "text": str(docs[i + j].get(text_field) or ""),
                        "tokens": enc[j][enc[j] != 0].tolist(),
                    }
                    if labels is not None:
                        row["label"] = int(labels[i + j])
                    preview.append(row)
            manifest = writer.close()
            if not tokenizer_from:
                # Commit point: shards are on disk, now the freshly
                # trained tokenizer may go live for tokenizerFrom.
                self.ctx.volumes.save_object(
                    TEXT_TYPE, _tokenizer_volume_name(name), tok
                )
            if preview:
                self.ctx.documents.insert_many(name, preview)
            out = {
                "fields": list(columns),
                "rows": len(docs),
                "sharded": True,
                "shards": len(manifest["shard_rows"]),
                "featureShape": [max_len],
                "vocabSize": tok.vocab_size,
                "tokenizer": tokenizer_from or name,
            }
            if classes is not None:
                out["labelClasses"] = classes
            return out

        self.ctx.engine.submit(
            name, tokenize,
            description=f"BPE tokenization of {parent_name}.{text_field}",
            on_success=lambda r: r,
            job_class="transform",
        )

    # -- generic transform (registry class + method) --------------------------

    def create_generic(
        self,
        name: str,
        *,
        module_path: str,
        class_name: str,
        class_parameters: dict | None = None,
        method: str | None = None,
        method_parameters: dict | None = None,
        artifact_type: str = "transform/tensorflow",
        description: str = "",
    ) -> dict:
        self.ctx.require_new_name(name)
        factory = registry.resolve(module_path, class_name)  # 406 if unknown
        bad = registry.validate_init_params(
            module_path, class_name, class_parameters or {}
        )
        if bad:
            raise ValidationError(f"invalid classParameters: {bad}")
        if method is not None:
            if not registry.validate_method(factory, method):
                raise ValidationError(f"no such method: {method!r}")
            bad = registry.validate_method_params(
                factory, method, method_parameters or {}
            )
            if bad:
                raise ValidationError(f"invalid methodParameters: {bad}")
        meta = self.ctx.artifacts.metadata.create(
            name,
            artifact_type,
            module_path=module_path,
            class_name=class_name,
            method=method,
            # Persisted so a PATCH re-run can rebuild the instance
            # without the original request body.
            extra={"classParameters": class_parameters or {}},
        )
        self._submit_generic(
            name, factory, class_parameters, method, method_parameters,
            artifact_type, description, class_name,
        )
        return meta

    def update_generic(
        self,
        name: str,
        *,
        class_parameters: dict | None = None,
        method_parameters: dict | None = None,
        description: str = "",
    ) -> dict:
        """PATCH re-run of a generic transform (reference:
        database_executor_image/server.py:91-148): re-executes with new
        parameters when given, else the original request's (class params
        from metadata, method params from the execution ledger)."""
        meta = self.ctx.require_not_running(name)
        module_path = meta.get("modulePath")
        class_name = meta.get("class")
        if not module_path or not class_name:
            raise ValidationError(
                f"{name!r} is not a re-runnable transform execution"
            )
        factory = registry.resolve(module_path, class_name)
        if class_parameters is None:
            class_parameters = meta.get("classParameters") or {}
        if method_parameters is None:
            method_parameters = self.ctx.last_recorded_parameters(name)
        method = meta.get("method")
        self.ctx.artifacts.metadata.restart(name)
        self._submit_generic(
            name, factory, class_parameters, method, method_parameters,
            meta.get("type"), description, class_name,
        )
        return self.ctx.artifacts.metadata.read(name)

    def _submit_generic(
        self, name, factory, class_parameters, method, method_parameters,
        artifact_type, description, class_name,
    ) -> None:
        def run():
            cls_params = dsl.resolve_params(
                class_parameters, self.ctx.loader
            )
            instance = factory(**cls_params)
            result = instance
            if method is not None:
                m_params = dsl.resolve_params(
                    method_parameters, self.ctx.loader
                )
                result = getattr(instance, method)(**m_params)
            self.ctx.volumes.save_object(artifact_type, name, result)
            return result

        self.ctx.engine.submit(
            name, run, description=description or f"{class_name}.{method}",
            method=method, parameters=method_parameters,
            job_class="transform",
        )
