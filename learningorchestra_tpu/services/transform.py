"""Transform service: projection, dtype casting, generic transform executor.

Reference parity:
- **projection** — column-select a dataset into a new collection; the
  reference runs this as a Spark job through the mongo-spark connector
  (microservices/projection_image/projection.py:20-48).  A column
  projection over a document store needs no cluster: here it is a
  batched host-side copy (and numeric transforms go through the JAX
  estimators instead).
- **dataType** — cast dataset fields string↔number in place, re-flagging
  the artifact unfinished while the cast runs
  (data_type_handler_image/data_type_update.py:15-59).
- **generic transform** — instantiate a registry class, call a method with
  DSL-treated params, persist the result binary
  (database_executor_image/database_execution.py:92-188).
"""

from __future__ import annotations

from learningorchestra_tpu import dsl
from learningorchestra_tpu.services.context import (
    ServiceContext,
    ValidationError,
)
from learningorchestra_tpu.toolkit import registry

PROJECTION_TYPE = "transform/projection"


def _compact_best_effort(documents, name: str) -> None:
    """WAL compaction is maintenance, never the job's outcome: a failed
    rewrite (transient disk/permission issue) must not fail a job whose
    actual work already committed."""
    if not hasattr(documents, "compact"):
        return
    try:
        documents.compact(name)
    except Exception as exc:  # noqa: BLE001 — maintenance only
        from learningorchestra_tpu.log import get_logger

        get_logger("store").warning(
            "compact(%s) failed (ignored): %r", name, exc
        )


class TransformService:
    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    # -- projection -----------------------------------------------------------

    def create_projection(
        self, name: str, parent_name: str, fields: list[str]
    ) -> dict:
        parent = self.ctx.require_finished_parent(parent_name)
        self.ctx.require_new_name(name)
        parent_fields = parent.get("fields") or []
        if parent_fields:
            missing = [f for f in fields if f not in parent_fields]
            if missing:
                raise ValidationError(
                    f"fields not in parent dataset: {missing}"
                )
        meta = self.ctx.artifacts.metadata.create(
            name, PROJECTION_TYPE, parent_name=parent_name,
            extra={"fields": fields},
        )
        self._submit_projection(name, parent_name, fields, replace=False)
        return meta

    def update_projection(
        self, name: str, fields: list[str] | None = None
    ) -> dict:
        """PATCH re-run (reference: PATCH /transform/projection →
        database_executor_image/server.py:91-148 — flip ``finished``
        False and re-execute): replaces the projected rows, with new
        ``fields`` when given, else the original request's."""
        meta = self.ctx.require_not_running(name)
        if meta.get("type") != PROJECTION_TYPE:
            raise ValidationError(f"{name!r} is not a projection")
        parent_name = meta.get("parentName")
        parent = self.ctx.require_finished_parent(parent_name)
        fields = fields or meta.get("fields") or []
        parent_fields = parent.get("fields") or []
        if parent_fields:
            missing = [f for f in fields if f not in parent_fields]
            if missing:
                raise ValidationError(
                    f"fields not in parent dataset: {missing}"
                )
        self.ctx.artifacts.metadata.restart(name)
        self._submit_projection(name, parent_name, fields, replace=True)
        return self.ctx.artifacts.metadata.read(name)

    def _submit_projection(
        self, name: str, parent_name: str, fields: list[str], *,
        replace: bool,
    ) -> None:
        def project():
            if replace:
                for doc in self.ctx.documents.find(
                    name,
                    query={
                        "_id": {"$gte": 1},
                        "docType": {"$ne": "execution"},
                    },
                ):
                    self.ctx.documents.delete_one(name, doc["_id"])
            if hasattr(self.ctx.documents, "project"):
                # Native scan: rows never materialize as Python objects
                # (the reference runs this as a Spark job over the
                # mongo connector; projection_image/projection.py:20-48).
                n = self.ctx.documents.project(parent_name, name, fields)
                if replace:
                    _compact_best_effort(self.ctx.documents, name)
                return {"rows": n, "fields": fields}
            docs = self.ctx.documents.find(
                parent_name,
                query={"_id": {"$gte": 1}, "docType": {"$ne": "execution"}},
            )
            out = (
                {f: d.get(f) for f in fields} for d in docs
            )
            n = self.ctx.documents.insert_many(name, out)
            if replace:
                # A replace wrote delete+insert WAL entries for every
                # row; fold the log back to current state.
                _compact_best_effort(self.ctx.documents, name)
            return {"rows": n, "fields": fields}

        self.ctx.engine.submit(
            name, project, description=f"projection of {parent_name}",
            parameters={"fields": fields},
            on_success=lambda r: r,
        )

    # -- dtype casting --------------------------------------------------------

    def update_field_types(self, parent_name: str, fields: dict) -> dict:
        """Cast fields in place; value ∈ {"number", "string"} per field
        (reference: data_type_handler_image/utils.py:87-102)."""
        meta = self.ctx.require_existing(parent_name)
        known = meta.get("fields") or []
        for field, kind in fields.items():
            if kind not in ("number", "string"):
                raise ValidationError(
                    f"field {field!r}: type must be 'number' or 'string'"
                )
            if known and field not in known:
                raise ValidationError(f"no such field: {field!r}")
        # Re-flag unfinished while the cast runs (reference:
        # data_type_update.py:47-59), then restore.
        self.ctx.artifacts.metadata.restart(parent_name)

        def cast():
            n_updates = 0
            docs = self.ctx.documents.find(
                parent_name,
                query={"_id": {"$gte": 1}, "docType": {"$ne": "execution"}},
            )
            for doc in docs:
                updates = {}
                for field, kind in fields.items():
                    val = doc.get(field)
                    if val is None:
                        continue
                    if kind == "number":
                        try:
                            updates[field] = float(val)
                        except (TypeError, ValueError):
                            updates[field] = None
                    else:
                        updates[field] = str(val)
                if updates:
                    self.ctx.documents.update_one(
                        parent_name, doc["_id"], updates
                    )
                    n_updates += 1
            if n_updates:
                # The cast appended one update entry per document; fold
                # the WAL back to current state.
                _compact_best_effort(self.ctx.documents, parent_name)
            return {"cast": list(fields)}

        self.ctx.engine.submit(
            parent_name, cast, description=f"dtype cast {fields}",
            on_success=lambda r: r,
        )
        return self.ctx.artifacts.metadata.read(parent_name)

    # -- generic transform (registry class + method) --------------------------

    def create_generic(
        self,
        name: str,
        *,
        module_path: str,
        class_name: str,
        class_parameters: dict | None = None,
        method: str | None = None,
        method_parameters: dict | None = None,
        artifact_type: str = "transform/tensorflow",
        description: str = "",
    ) -> dict:
        self.ctx.require_new_name(name)
        factory = registry.resolve(module_path, class_name)  # 406 if unknown
        bad = registry.validate_init_params(
            module_path, class_name, class_parameters or {}
        )
        if bad:
            raise ValidationError(f"invalid classParameters: {bad}")
        if method is not None:
            if not registry.validate_method(factory, method):
                raise ValidationError(f"no such method: {method!r}")
            bad = registry.validate_method_params(
                factory, method, method_parameters or {}
            )
            if bad:
                raise ValidationError(f"invalid methodParameters: {bad}")
        meta = self.ctx.artifacts.metadata.create(
            name,
            artifact_type,
            module_path=module_path,
            class_name=class_name,
            method=method,
            # Persisted so a PATCH re-run can rebuild the instance
            # without the original request body.
            extra={"classParameters": class_parameters or {}},
        )
        self._submit_generic(
            name, factory, class_parameters, method, method_parameters,
            artifact_type, description, class_name,
        )
        return meta

    def update_generic(
        self,
        name: str,
        *,
        class_parameters: dict | None = None,
        method_parameters: dict | None = None,
        description: str = "",
    ) -> dict:
        """PATCH re-run of a generic transform (reference:
        database_executor_image/server.py:91-148): re-executes with new
        parameters when given, else the original request's (class params
        from metadata, method params from the execution ledger)."""
        meta = self.ctx.require_not_running(name)
        module_path = meta.get("modulePath")
        class_name = meta.get("class")
        if not module_path or not class_name:
            raise ValidationError(
                f"{name!r} is not a re-runnable transform execution"
            )
        factory = registry.resolve(module_path, class_name)
        if class_parameters is None:
            class_parameters = meta.get("classParameters") or {}
        if method_parameters is None:
            method_parameters = self.ctx.last_recorded_parameters(name)
        method = meta.get("method")
        self.ctx.artifacts.metadata.restart(name)
        self._submit_generic(
            name, factory, class_parameters, method, method_parameters,
            meta.get("type"), description, class_name,
        )
        return self.ctx.artifacts.metadata.read(name)

    def _submit_generic(
        self, name, factory, class_parameters, method, method_parameters,
        artifact_type, description, class_name,
    ) -> None:
        def run():
            cls_params = dsl.resolve_params(
                class_parameters, self.ctx.loader
            )
            instance = factory(**cls_params)
            result = instance
            if method is not None:
                m_params = dsl.resolve_params(
                    method_parameters, self.ctx.loader
                )
                result = getattr(instance, method)(**m_params)
            self.ctx.volumes.save_object(artifact_type, name, result)
            return result

        self.ctx.engine.submit(
            name, run, description=description or f"{class_name}.{method}",
            method=method, parameters=method_parameters,
        )
