"""Shared service context: store + volumes + job engine + artifact loader.

Also defines the request-validation exceptions the API layer maps onto the
reference's status codes (409 duplicate, 404 missing, 406 semantic errors —
reference: microservices/binary_executor_image/server.py:332-398).
"""

from __future__ import annotations

from typing import Any

import pandas as pd

from learningorchestra_tpu.config import Config, get_config
from learningorchestra_tpu.jobs import JobEngine
from learningorchestra_tpu.log import get_logger, kv
from learningorchestra_tpu.store import (
    ArtifactStore,
    VolumeStorage,
    open_document_store,
)


import re

# Same shape the document store enforces (document_store._NAME_RE):
# first char word-like, no separators — '..' and '/x' can never match.
_ARTIFACT_NAME_RE = re.compile(r"[A-Za-z0-9_][A-Za-z0-9_.\-]*")


class ValidationError(Exception):
    """Semantic request error → HTTP 406 (reference's NOT_ACCEPTABLE)."""


class NotFoundError(Exception):
    """Missing artifact → HTTP 404."""


class ConflictError(Exception):
    """Duplicate artifact name → HTTP 409."""


class ServiceContext:
    def __init__(self, config: Config | None = None):
        self.config = config or get_config()
        self.documents = open_document_store(
            self.config.store.store_path(),
            durable_writes=self.config.store.durable_writes,
            backend=self.config.store.backend,
        )
        self.artifacts = ArtifactStore(self.documents)
        self.volumes = VolumeStorage(self.config.store.volume_path())
        self.engine = JobEngine(
            self.artifacts,
            max_workers=self.config.jobs.max_workers,
            max_preemption_retries=(
                self.config.jobs.max_preemption_retries
            ),
            class_weights=self.config.jobs.class_weights,
            retry_backoff_s=self.config.jobs.retry_backoff_s,
            retry_backoff_max_s=self.config.jobs.retry_backoff_max_s,
            deadline_s=self.config.jobs.deadline_s,
            shutdown_drain_s=self.config.jobs.shutdown_drain_s,
        )
        self.loader = StoreLoader(self)
        from learningorchestra_tpu.services.webhooks import (
            WebhookNotifier,
        )

        # Observe PUSH path: job completion fires registered webhooks
        # (the reference's pub/sub Observe shape, README.md:71).
        self.webhooks = WebhookNotifier(self.documents)
        self.engine.notifier = self.webhooks
        from learningorchestra_tpu.jobs.leases import DeviceLeaser

        # Per-job accelerator placement (jobs/leases.py): concurrent
        # neural jobs serialize per chip instead of contending for HBM.
        # The engine's deadline watchdog revokes an expired job's
        # leases through the same pool.
        self.leaser = DeviceLeaser()
        self.engine.leaser = self.leaser
        # When the compiled-program cache clears on a device-set change
        # (TPU restart / tunnel reattach), the engine's warm-start
        # hints are stale — 'warm' jobs would trace like any other.
        # Weakly bound: short-lived contexts (tests) must not pin dead
        # engines through the process-global cache.
        import weakref

        from learningorchestra_tpu.train import compile_cache

        engine_ref = weakref.ref(self.engine)

        def _drop_warm_hints():
            engine = engine_ref()
            if engine is not None:
                engine.clear_warm_keys()

        # Keep the handle so close() can deregister — the cache is
        # process-global and must not accumulate dead listeners across
        # short-lived contexts.
        self._warm_hint_listener = _drop_warm_hints
        compile_cache.get_cache().add_invalidation_listener(
            _drop_warm_hints
        )
        # Artifact-change fan-out: anything holding derived state keyed
        # by artifact name (the serving registry's device-resident
        # params, serve/registry.py) subscribes here; delete and
        # binary-overwrite paths notify so stale state is dropped
        # before the next read.
        self._artifact_change_listeners: list = []
        # Scale-out control plane (jobs/cluster.py): when enabled, N
        # engine processes over ONE store root share dispatch through
        # the store-backed claim table.  Constructed BEFORE the
        # journal so epoch minting runs under the cluster's
        # cross-process lock (two engines booting concurrently must
        # mint distinct epochs).  Requires the python store backend —
        # the native backend has no WAL-refresh coherence primitive,
        # so clustering is LOUDLY disabled rather than silently
        # incoherent.
        self.cluster = None
        self.admission = None
        if self.config.cluster.enabled:
            if not hasattr(self.documents, "refresh"):
                get_logger("context").error(
                    "LO_TPU_CLUSTER_ENABLED requires the python "
                    "store backend (LO_TPU_STORE_BACKEND=python): "
                    "the native backend has no WAL-refresh coherence "
                    "primitive — clustering DISABLED for this process"
                )
            else:
                from learningorchestra_tpu.jobs.cluster import (
                    ClusterCoordinator,
                )

                self.cluster = ClusterCoordinator(
                    self.documents,
                    self.config.store.store_path(),
                    engine_id=self.config.cluster.engine_id,
                    heartbeat_s=self.config.cluster.heartbeat_s,
                    ttl_s=self.config.cluster.ttl_s,
                    sweep_s=self.config.cluster.sweep_s,
                )
        # Per-tenant fair-share admission: constructed whenever a
        # quota is configured; store-backed counters when clustered so
        # every engine enforces identically.
        if (
            self.config.tenant.max_queued > 0
            or self.config.tenant.max_running > 0
        ):
            from learningorchestra_tpu.jobs.cluster import (
                TenantAdmission,
            )

            self.admission = TenantAdmission(
                max_queued=self.config.tenant.max_queued,
                max_running=self.config.tenant.max_running,
                retry_after_s=self.config.tenant.retry_after_s,
                cluster=self.cluster,
            )
        self.engine.admission = self.admission
        # Crash-durable job journal + engine-epoch fencing
        # (jobs/journal.py): construction mints this boot's engine
        # epoch, so any straggler from a previous life is refused at
        # its terminal commit.  The engine appends every transition
        # through it.
        from learningorchestra_tpu.jobs.journal import JobJournal

        self.journal = JobJournal(
            self.documents,
            self.config.store.store_path(),
            enabled=self.config.jobs.journal,
            max_records=self.config.jobs.journal_max_records,
            epoch_lock=(
                (lambda: self.cluster._guard(refresh=()))
                if self.cluster is not None else None
            ),
        )
        self.engine.journal = (
            self.journal if self.journal.enabled else None
        )
        if self.cluster is not None:
            # Wire the plane together: the coordinator publishes this
            # boot's epoch on every claim; the journal's fence
            # delegates to claim ownership and its appends/replays run
            # under the cross-process guard; the engine claims before
            # every dispatch.  join() starts heartbeat + sweep.
            self.cluster.epoch = self.journal.epoch
            self.cluster.on_steal = self._cluster_steal
            self.cluster.on_engine_dead = self._cluster_engine_dead
            if self.journal.enabled:
                self.journal.cluster = self.cluster
                self.journal.exclusive = self.cluster.journal_guard
            self.engine.cluster = self.cluster
            self.cluster.join()
        # Backend init FIRST: recovery may re-dispatch train fits,
        # and job threads racing first-time backend init deadlock
        # inside xla_bridge (the race _init_backend exists to remove).
        self._init_backend()
        if self.cluster is not None:
            with self.cluster.journal_guard():
                self.journal.prune()
        else:
            self.journal.prune()
        self._recover_jobs()
        # Durable warm start: restore the persisted AOT hot set into
        # the compile cache on a background thread, so recovered fits
        # and the first post-deploy requests hit warm executables
        # instead of re-tracing (ROADMAP item 3).
        self._aot_prewarm_thread = None
        self._start_aot_prewarm()

    def add_artifact_change_listener(self, listener) -> None:
        """Register ``listener(name)`` to fire when an artifact's
        binary or metadata is replaced or deleted.  Listeners must be
        fast and must not raise (exceptions are swallowed — a broken
        subscriber must not fail a delete)."""
        self._artifact_change_listeners.append(listener)

    def notify_artifact_changed(self, name: str) -> None:
        for listener in self._artifact_change_listeners:
            try:
                listener(name)
            except Exception:  # noqa: BLE001 — never fail the mutation
                pass

    def _recover_jobs(self) -> None:
        """Boot-time restart recovery over the job journal.

        Any pending/running jobState at startup belonged to a DEAD
        process — this process hasn't run a job yet.  Left alone it
        wedges the artifact forever: the job will never finish, and
        ``require_not_running`` would 409 every PATCH re-run.  Matters
        most after store failover, where the promoted standby inherits
        the killed primary's in-flight states (and its journal)
        through the shipped WAL.

        With the journal enabled and ``jobs.journal_recover`` on,
        journaled jobs whose bodies are re-dispatchable are RESUBMITTED
        through the existing PATCH machinery, in their pre-crash queue
        order: train fits resume from their newest managed checkpoint
        (services/executor.py's resume path), distributed fits through
        ``update_train``.  Everything else — and every job when
        recovery is off — is terminally failed with an explicit
        ``orphaned-by-restart`` reason instead of leaving phantom
        "running" metadata; jobs with NO journal record (stores that
        predate the journal, or a disabled journal) keep the legacy
        interrupted-re-flag message.  The reference re-flags
        unfinished work at service startup
        (data_type_handler_image/data_type_update.py:47-59); this
        resolves it into automatic resumption.
        """
        journaled = (
            self.journal.replay() if self.journal.enabled else {}
        )
        recover = (
            self.journal.enabled and self.config.jobs.journal_recover
        )
        interrupted: list[tuple] = []
        for name in self.documents.list_collections():
            if name.startswith("_"):
                continue  # internal ledgers/journal have no jobs
            try:
                meta = self.artifacts.metadata.read(name)
            except Exception:
                continue
            if not meta or meta.get("jobState") not in (
                "pending", "running"
            ):
                continue
            if (
                self.cluster is not None
                and not self.cluster.claimable(name)
            ):
                # A LIVE peer engine holds this job's claim: the job
                # is running over there, not orphaned here — adopting
                # it would be the double-run.  If that peer dies, the
                # sweep steals the claim and resumes it then.
                continue
            rec = journaled.get(name)
            # Re-enqueue order = pre-crash queue admission order (the
            # journal's latest `queued` sequence number); journal-less
            # jobs go last, name-ordered for determinism.
            seq = (
                rec["seq"] if rec and rec["seq"] >= 0
                else float("inf")
            )
            interrupted.append((seq, name, meta, rec))
        interrupted.sort(key=lambda t: (t[0], t[1]))
        log = get_logger("context")
        for _seq, name, meta, rec in interrupted:
            kind = (
                self._recoverable_kind(meta)
                # A journal-terminal record under non-terminal
                # metadata means the job's life ENDED (refused
                # submission, or a crash between the journal append
                # and the metadata commit) — orphan it, don't
                # resurrect it.
                if recover and rec is not None
                and not rec.get("terminal")
                else None
            )
            if kind is None:
                self._orphan_job(name, journaled=rec is not None)
                continue
            try:
                self._redispatch(name, kind, rec.get("spec") or {})
                log.warning(
                    f"recovered job {name!r} from the journal "
                    f"(epoch {self.journal.epoch}): re-dispatched "
                    "through the checkpoint-resume path"
                )
            except Exception as exc:  # noqa: BLE001 — recovery must
                # finish: one unrecoverable job (deleted parent, bad
                # spec) must not wedge the whole boot.
                log.error(
                    f"could not re-dispatch recovered job {name!r}: "
                    f"{exc!r} — failing it orphaned-by-restart"
                )
                self._orphan_job(
                    name, journaled=True, detail=repr(exc)
                )

    @staticmethod
    def _recoverable_kind(meta: dict) -> str | None:
        """How a journaled job can be re-dispatched, or None.

        Executor-family artifacts re-run through the PATCH path with
        their last recorded parameters; tune grids are excluded (a
        grid re-submission is not expressible through the generic
        PATCH — their trials resume only across in-engine preemption
        retries) and so is anything without a parent/method spec
        (functions, models: their bodies are not derivable from
        metadata alone)."""
        if meta.get("distributed"):
            return "distributed"
        kind = str(meta.get("type", ""))
        if (
            kind.startswith(("train/", "evaluate/", "predict/"))
            and meta.get("parentName")
            and meta.get("method")
        ):
            return "executor"
        return None

    def _redispatch(self, name: str, kind: str, spec: dict) -> None:
        """Resubmit a recovered job through the existing PATCH
        machinery, carrying the journaled submit spec forward (a job
        submitted with a deadline must resume under it, not under the
        engine default).  Marking it failed FIRST is what routes a
        train fit into the checkpoint-resume path (update() resumes
        failed jobs from their newest managed checkpoint instead of
        epoch 0)."""
        self.artifacts.metadata.mark_failed(
            name,
            "orphaned-by-restart: re-dispatching from the job journal",
        )
        description = spec.get("description") or ""
        if kind == "distributed":
            from learningorchestra_tpu.services.distributed_exec import (
                DistributedExecutorService,
            )

            DistributedExecutorService(self, None).update_train(
                name, description=description
            )
        else:
            from learningorchestra_tpu.services.executor import (
                ExecutorService,
            )

            ExecutorService(self).update(
                name,
                description=description,
                deadline_s=spec.get("deadlineS"),
            )

    def _orphan_job(self, name: str, *, journaled: bool,
                    detail: str | None = None) -> None:
        """Terminally fail an interrupted job that cannot (or must
        not) be re-dispatched — never leave phantom 'running'
        metadata."""
        if journaled:
            reason = (
                "orphaned-by-restart: the orchestrator died while "
                "this job was queued or running and its body is not "
                "automatically re-dispatchable"
                + (f" ({detail})" if detail else "")
                + "; re-run it with a PATCH (bare PATCH re-uses the "
                "last recorded parameters)"
            )
        else:
            reason = (
                "job interrupted by a server restart or store "
                "failover before completing; re-run it with a "
                "PATCH (bare PATCH re-uses the last recorded "
                "parameters)"
            )
        self.artifacts.metadata.mark_failed(name, reason)
        if journaled:
            self.journal.append(
                "failed", name, reason="orphaned-by-restart"
            )
        get_logger("context").warning(
            f"re-flagged interrupted job {name!r} "
            "(was mid-run when the previous process died)"
        )
        # Subscribers must see the terminal transition: the
        # observe event feed + any registered webhooks fire
        # exactly as the engine's own failure path would
        # (jobs/engine.py _notify) — a watcher of the dead
        # job would otherwise wait forever.
        try:
            self.webhooks.notify(
                name, "failed",
                self.artifacts.metadata.read(name) or {},
            )
        except Exception:  # noqa: BLE001 — startup must finish
            pass

    def _cluster_steal(self, job: str, prev_engine: str) -> None:
        """Sweep callback: this engine now owns a claim stolen from a
        dead (or partitioned) peer.  Re-read the job's TRUE state from
        the shared store and either close it out (the peer finished it
        before dying) or resume it through the same checkpoint-resume
        machinery boot recovery uses.  The stolen claim stays ours
        across the re-dispatch (the dispatch-time claim() renews it),
        so a revived straggler is fenced at its terminal commit."""
        log = get_logger("context")
        try:
            if hasattr(self.documents, "refresh"):
                # The dead peer's process wrote this job's collection;
                # fold its WAL tail into our in-memory view first.
                self.documents.refresh(job)
            replayed = self.journal.replay()
            rec = replayed.get(job)
            if rec is not None and rec.get("terminal"):
                # Finished/failed before the peer died — release the
                # claim (its doneAt supersedes stale queue entries)
                # and touch nothing.
                self.cluster.release(job)
                return
            meta = self.artifacts.metadata.read(job)
            if meta is None:
                self.cluster.release(job)
                return
            kind = self._recoverable_kind(meta)
            if kind is None:
                self._orphan_job(job, journaled=rec is not None)
                self.cluster.release(job)
                return
            self._redispatch(job, kind, (rec or {}).get("spec") or {})
            log.warning(
                f"stole job {job!r} from engine {prev_engine!r} "
                f"(epoch {self.journal.epoch}): re-dispatched "
                "through the checkpoint-resume path"
            )
        except Exception as exc:  # noqa: BLE001 — one bad adoption
            # must not kill the sweep loop.
            log.error(
                f"could not adopt stolen job {job!r}: {exc!r} — "
                "failing it orphaned-by-restart"
            )
            try:
                self._orphan_job(job, journaled=True,
                                 detail=repr(exc))
                self.cluster.release(job)
            except Exception:  # noqa: BLE001
                pass

    def _cluster_engine_dead(self, engine_id: str, epoch: int) -> None:
        """Sweep callback: a peer engine's membership expired.  Its
        RUNNING jobs are adopted by the steal path (they hold claims);
        this adopts its QUEUED-but-never-claimed jobs — journaled
        under the dead epoch, non-terminal, no live claim — in
        pre-crash queue order.  A racing duplicate (the 'dead' engine
        was only partitioned and still dispatches its copy) is safe:
        both race the dispatch-time claim CAS and exactly one runs."""
        log = get_logger("context")
        try:
            replayed = self.journal.replay()
        except Exception:  # noqa: BLE001
            return
        work = sorted(
            (
                (rec.get("seq", -1), job, rec)
                for job, rec in replayed.items()
                if rec.get("epoch") == epoch
                and not rec.get("terminal")
                and rec.get("state") in ("submitted", "queued")
            ),
            key=lambda t: (t[0], t[1]),
        )
        for _seq, job, rec in work:
            if not self.cluster.claimable(job):
                continue
            try:
                if hasattr(self.documents, "refresh"):
                    self.documents.refresh(job)
                meta = self.artifacts.metadata.read(job)
                kind = (
                    self._recoverable_kind(meta)
                    if meta is not None else None
                )
                if kind is None:
                    if meta is not None and meta.get("jobState") in (
                        "pending", "running"
                    ):
                        self._orphan_job(job, journaled=True)
                    continue
                self._redispatch(job, kind, rec.get("spec") or {})
                log.warning(
                    f"adopted queued job {job!r} from dead engine "
                    f"{engine_id!r} (epoch {epoch})"
                )
            except Exception as exc:  # noqa: BLE001
                log.error(
                    f"could not adopt queued job {job!r} from dead "
                    f"engine {engine_id!r}: {exc!r}"
                )

    def require_current_epoch(self) -> None:
        """Epoch fence at artifact-publication time: a job body from a
        stale engine epoch (pre-crash straggler, or a partitioned
        duplicate orchestrator once the control plane goes
        multi-process) raises :class:`~learningorchestra_tpu.jobs.
        journal.StaleEpochError` here instead of double-publishing.
        No-op outside an engine dispatch."""
        self.journal.fence_check()

    def _start_aot_prewarm(self) -> None:
        """Kick off the boot pre-warm when the durable AOT store is on
        (``LO_TPU_AOT_ENABLED`` + ``LO_TPU_AOT_PREWARM``) and has a
        manifest to walk.  Background by design: restoring executables
        costs device-time seconds and must not gate readiness — the
        API comes up immediately; programs not yet restored simply
        build live as before."""
        from learningorchestra_tpu.train import aot_store, compile_cache

        try:
            if not (
                aot_store.enabled()
                and self.config.aot.prewarm
                and compile_cache.enabled()
            ):
                return
            store = aot_store.get_store()
            work = store.manifest_entries() if store is not None else []
        except Exception:  # noqa: BLE001 — warm start is best-effort
            return
        if not work:
            return
        import threading

        self._aot_prewarm_thread = threading.Thread(
            target=self._aot_prewarm, args=(store, work),
            name="aot-prewarm", daemon=True,
        )
        self._aot_prewarm_thread.start()

    def _aot_prewarm(self, store, work: list[dict]) -> None:
        """Walk the manifest hottest-first, deserializing each blob and
        installing the restored executable into the compile cache.
        Every restore is a span on a dedicated boot trace
        (``boot.prewarm`` — the trace surfaces in logs; per-key
        failures degrade to live builds, never crash the boot)."""
        import time

        from learningorchestra_tpu.obs import tracing
        from learningorchestra_tpu.train import compile_cache

        cache = compile_cache.get_cache()
        trace = tracing.new_trace("boot.prewarm")
        warmed = skipped = failed = 0
        t0 = time.perf_counter()
        with tracing.activate(trace):
            for rec in work:
                key = rec.get("key")
                if not key or cache.contains(key):
                    skipped += 1
                    continue
                label = rec.get("label")
                try:
                    with tracing.span(
                        "prewarm", key=key[:12], label=label or "",
                    ):
                        compiled = store.load(key)
                        if compiled is None:
                            failed += 1
                            continue
                        ok = cache.install(
                            key,
                            compile_cache._AOTRestored(
                                compiled, None, key, label
                            ),
                            label=label,
                            nbytes=rec.get("bytes"),
                        )
                    warmed += 1 if ok else 0
                except Exception:  # noqa: BLE001 — a bad blob costs
                    failed += 1    # one key, not the boot
        get_logger("services").info(kv(
            event="aot_prewarm_done", warmed=warmed, skipped=skipped,
            failed=failed, total=len(work),
            seconds=round(time.perf_counter() - t0, 3),
        ))

    def _init_backend(self) -> None:
        """Eagerly initialize the JAX backend on the main thread.

        Two job threads racing first-time backend init deadlock inside
        xla_bridge (observed with concurrent fits on worker threads);
        paying init once at service startup removes the race and also
        front-loads the TPU client handshake out of the first job's
        latency.  The persistent compilation cache means a re-submitted
        job (or a restarted server) skips the 20-40s TPU compile."""
        import os

        import jax

        cache_dir = self.config.store.xla_cache_dir
        if cache_dir:
            try:
                path = os.path.expanduser(cache_dir)
                os.makedirs(path, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", path)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0
                )
            except Exception:
                pass  # cache is an optimization, never a failure
        jax.devices()

    def close(self) -> None:
        from learningorchestra_tpu.train import compile_cache

        compile_cache.get_cache().remove_invalidation_listener(
            getattr(self, "_warm_hint_listener", None)
        )
        # Bounded wait for an in-flight boot pre-warm (daemon thread):
        # installs racing a closing process are harmless — the compile
        # cache is process-global — but a short join keeps test
        # teardown deterministic.
        thread = getattr(self, "_aot_prewarm_thread", None)
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        # With a drain budget configured (LO_TPU_JOB_DRAIN_S — both
        # deploy manifests set one) the graceful path WAITS, bounded:
        # running bodies get their cancel tokens flipped past the
        # budget and stragglers are abandoned after a grace.  Without
        # one, keep the legacy non-blocking close (never hang a
        # SIGTERM on an unbounded drain).
        self.engine.shutdown(
            wait=self.config.jobs.shutdown_drain_s > 0
        )
        # Journal AFTER the engine (shutdown journals its cancelled
        # drops), BEFORE the store (a drain into closed WAL handles
        # would drop every record).  The cluster leaves after the
        # journal's final drain (its guard serializes that drain) and
        # before the store closes (retracting the membership document
        # is a store write).
        self.journal.close()
        if self.cluster is not None:
            self.cluster.close()
        self.documents.close()

    # -- validation helpers shared by services --------------------------------

    def require_new_name(self, name: str) -> None:
        if not name or not isinstance(name, str):
            raise ValidationError("missing or invalid 'name'")
        # Artifact names become collection files, volume paths AND
        # checkpoint directories; reject path-shaped names here (406)
        # rather than relying on the store's internal gate (500) — and
        # never let '..'/absolute names reach a shutil.rmtree.
        if not _ARTIFACT_NAME_RE.fullmatch(name) or ".." in name:
            raise ValidationError(f"invalid artifact name: {name!r}")
        # Reserved: text transforms store their trained tokenizer
        # binary at "<artifact>.tokenizer" in the shared transform
        # volume (services/transform.py::_tokenizer_volume_name); an
        # artifact claiming such a name would collide with it.
        if name.endswith(".tokenizer"):
            raise ValidationError(
                f"artifact name {name!r} uses the reserved "
                "'.tokenizer' suffix"
            )
        # Reserved: these segments are fixed observe sub-routes
        # (GET /observe/events, POST /observe/webhook); an artifact so
        # named would be silently shadowed off the observe long-poll.
        # MIGRATION CAVEAT (ADVICE r3): a store that predates this
        # gate may already hold an artifact named "events"/"webhook";
        # its /observe/<name> long-poll and per-artifact webhook routes
        # are permanently shadowed by the fixed routes.  Rename such
        # artifacts before upgrading (the data itself remains readable
        # via the service GET routes, which are not shadowed).
        if name in ("events", "webhook"):
            raise ValidationError(
                f"artifact name {name!r} is reserved (observe route)"
            )
        if self.artifacts.metadata.exists(name):
            raise ConflictError(f"duplicate artifact name: {name!r}")

    def require_existing(self, name: str) -> dict:
        meta = self.artifacts.metadata.read(name)
        if meta is None:
            raise NotFoundError(f"no such artifact: {name!r}")
        return meta

    def require_not_running(self, name: str) -> dict:
        """PATCH re-run gate: two jobs for one artifact must not run
        concurrently (each would interleave delete/insert over the same
        collection and flip ``finished`` under the other) — 409 while
        the previous job is still executing."""
        meta = self.require_existing(name)
        if meta.get("jobState") in ("pending", "running"):
            raise ConflictError(
                f"artifact {name!r} has a job in state "
                f"{meta.get('jobState')!r}; wait for it to finish"
            )
        return meta

    def last_recorded_parameters(self, name: str):
        """The most recent request parameters persisted for ``name`` —
        the fallback a bare PATCH re-run (no body parameters, the
        natural "just resume" call after a preemption or failover)
        re-submits with, instead of failing on missing x/y.  Terminal
        ledger rows win (they reflect what actually ran); the
        submit-time metadata copy covers a job whose FIRST run died
        before writing any ledger record."""
        rows = [
            d
            for d in self.documents.find(
                name, query={"docType": "execution"}
            )
            if d.get("parameters") is not None
        ]
        if rows:
            return rows[-1]["parameters"]
        meta = self.artifacts.metadata.read(name) or {}
        return meta.get("requestParameters")

    def checkpoint_dir(self, name: str):
        """Managed per-artifact train-checkpoint tree — the ONE place
        this path is built (executor, distributed route and delete all
        share it)."""
        return self.volumes.root / "_checkpoints" / name

    def delete_artifact(self, name: str) -> dict:
        """Shared delete: collection + volume binary (dataset/model/
        executor/function services all expose the same DELETE), plus any
        managed train checkpoints — a recreated artifact with the same
        name must never resume from a deleted job's state."""
        meta = self.require_existing(name)
        self.artifacts.delete(name)
        self.volumes.delete(meta.get("type", ""), name)
        # Serving registry (and any other subscriber) must drop
        # resident state derived from this artifact NOW — a recreated
        # artifact with the same name must never serve deleted weights.
        self.notify_artifact_changed(name)
        # A text transform also owns a trained-tokenizer binary next to
        # its shard directory; deleting the artifact must not leave it
        # behind (a later tokenizerFrom would silently load the stale
        # vocab of a name that no longer exists).
        if meta.get("type") == "transform/text":
            self.volumes.delete(
                meta.get("type", ""), name + ".tokenizer"
            )
        import shutil

        ckdir = self.checkpoint_dir(name)
        if ckdir.exists():
            shutil.rmtree(ckdir, ignore_errors=True)
        return meta

    def require_finished_parent(self, name: str) -> dict:
        """Downstream steps refuse unfinished parents (reference:
        projection_image/utils.py:88-95)."""
        meta = self.require_existing(name)
        if not meta.get("finished"):
            raise ValidationError(
                f"parent artifact {name!r} is not finished "
                f"(jobState={meta.get('jobState')})"
            )
        return meta


class StoreLoader:
    """The DSL's ``$name`` resolution over store + volumes.

    Mirrors the reference's load rules (binary_executor_image/
    utils.py:322-336): dataset collections load as DataFrames; everything
    else loads its volume binary (checkpointed estimator / pytree / raw
    object)."""

    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    def load(self, name: str) -> Any:
        meta = self.ctx.artifacts.metadata.read(name)
        if meta is None:
            raise KeyError(name)
        kind = str(meta.get("type", ""))
        if meta.get("sharded"):
            # Beyond-RAM datasets resolve to a LAZY handle (train paths
            # stream its shards); materializing a DataFrame here would
            # be exactly the O(dataset)-host-memory step the sharded
            # format exists to avoid.  ``$name.col`` indexes to a
            # single-column view via ShardedDataset.__getitem__.
            from learningorchestra_tpu.store.sharded import ShardedDataset

            return ShardedDataset(self.ctx.volumes.path_for(kind, name))
        if kind.startswith("dataset/csv") or not self.ctx.volumes.exists(
            kind, name
        ):
            return self.load_dataframe(name)
        return self.ctx.volumes.read_object(kind, name)

    def load_dataframe(self, name: str) -> pd.DataFrame:
        docs = self.ctx.documents.find(
            name,
            query={"_id": {"$gte": 1}, "docType": {"$ne": "execution"}},
        )
        if not docs:
            raise KeyError(f"artifact {name!r} has no rows")
        df = pd.DataFrame(docs)
        return df.drop(columns=["_id"])
