"""Observe push notifications — webhooks on artifact state transitions.

The reference's Observe service is a collection watch/pub-sub: clients
subscribe and get PUSHED a message when a pipeline step finishes
(reference: README.md:71 "observe... a wait until a processing step
finish"; the Python client blocks on a Mongo change stream).  Round 2
covered the WAIT shape with the ``GET /observe/<name>`` long-poll; this
module adds the PUSH shape: register a webhook URL against an artifact
and the job engine's completion path fires an HTTP POST at it on
``finished``/``failed`` — no polling connection held open.

Registrations are documents in the store (collection
``observe_webhooks``), so they survive restarts like every other
artifact.  Delivery is fire-and-forget on a daemon thread with bounded
retries; the registration doc records the last delivery outcome for
debugging (``lastStatus``/``lastError``/``deliveries``).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

from learningorchestra_tpu.log import get_logger, kv
from learningorchestra_tpu.store.document_store import NoSuchCollection

COLLECTION = "observe_webhooks"
EVENTS_COLLECTION = "observe_events"
EVENTS = ("finished", "failed")
WILDCARD = "*"  # register against every artifact
EVENT_RETAIN = 10_000  # feed rows kept (pruned probabilistically)


class WebhookNotifier:
    def __init__(self, documents, *, attempts: int = 3,
                 timeout_s: float = 10.0):
        self.documents = documents
        self.attempts = attempts
        self.timeout_s = timeout_s
        self.log = get_logger("observe")

    # -- registry -------------------------------------------------------------

    def register(self, artifact: str, url: str,
                 events: list[str] | None = None) -> dict:
        """``artifact="*"`` registers a WILDCARD hook fired for every
        artifact — the reference Observe's watch-anything shape."""
        if not url or not url.startswith(("http://", "https://")):
            raise ValueError(
                f"webhook url must be http(s), got {url!r}"
            )
        events = list(events or EVENTS)
        bad = [e for e in events if e not in EVENTS]
        if bad:
            raise ValueError(
                f"unknown webhook events {bad}; valid: {list(EVENTS)}"
            )
        doc = {
            "artifact": artifact,
            "url": url,
            "events": events,
            "deliveries": 0,
            "lastStatus": None,
            "lastError": None,
        }
        _id = self.documents.insert_one(COLLECTION, doc)
        return {**doc, "_id": _id}

    def unregister(self, artifact: str, hook_id: int) -> bool:
        doc = self.documents.find_one(COLLECTION, hook_id)
        if doc is None or doc.get("artifact") != artifact:
            return False
        return self.documents.delete_one(COLLECTION, hook_id)

    def list(self, artifact: str) -> list[dict]:
        try:
            return self.documents.find(
                COLLECTION, query={"artifact": artifact}
            )
        except NoSuchCollection:
            return []  # nothing ever registered on this store

    # -- firing ---------------------------------------------------------------

    def deliver_to(self, hook: dict, artifact: str, event: str,
                   metadata: dict) -> None:
        """Deliver one registration's POST without touching the event
        feed or other hooks — the immediate-fire path for a webhook
        registered on an ALREADY-terminal artifact (the transition was
        recorded and wildcard-delivered when it actually happened)."""
        payload = json.dumps({
            "name": artifact,
            "event": event,
            "metadata": metadata,
        }).encode()
        threading.Thread(
            target=self._deliver_all,
            args=([hook], payload),
            name="webhook-notify",
            daemon=True,
        ).start()

    def notify(self, artifact: str, event: str, metadata: dict) -> None:
        """Fire registered webhooks for (artifact, event) — returns
        immediately; delivery happens on a daemon thread so a slow or
        dead endpoint can never stall the job engine's completion
        path."""
        self.record_event(artifact, event, metadata)
        try:
            hooks = [
                h for h in self.list(artifact) + self.list(WILDCARD)
                if event in h.get("events", EVENTS)
            ]
        except Exception:  # noqa: BLE001 — notify must never raise
            return
        if not hooks:
            return
        payload = json.dumps({
            "name": artifact,
            "event": event,
            "metadata": metadata,
        }).encode()
        threading.Thread(
            target=self._deliver_all,
            args=(hooks, payload),
            name="webhook-notify",
            daemon=True,
        ).start()

    def _deliver_all(self, hooks: list[dict], payload: bytes) -> None:
        for hook in hooks:
            status, error = self._deliver(hook["url"], payload)
            try:
                self.documents.update_one(COLLECTION, hook["_id"], {
                    "deliveries": hook.get("deliveries", 0) + 1,
                    "lastStatus": status,
                    "lastError": error,
                })
            except Exception:  # noqa: BLE001 — bookkeeping is best-effort
                pass

    def _deliver(self, url: str, payload: bytes):
        last_err = None
        for attempt in range(self.attempts):
            try:
                req = urllib.request.Request(
                    url, data=payload,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as resp:
                    return resp.status, None
            except Exception as exc:  # noqa: BLE001
                last_err = repr(exc)
                self.log.warning(kv(
                    webhook=url, attempt=attempt + 1, error=last_err
                ))
                if attempt + 1 < self.attempts:
                    # No trailing sleep after the FINAL failure — it
                    # would only delay delivery to the next hook.
                    time.sleep(min(2 ** attempt, 5))
        return None, last_err

    # -- event feed -----------------------------------------------------------

    def record_event(self, artifact: str, event: str,
                     metadata: dict) -> None:
        """Append to the global event feed (collection
        ``observe_events``) — the pull twin of the webhook push: one
        ordered stream of every artifact state transition, cursorable
        by ``_id`` (atomic per-collection ids are the sequence).
        Never raises; the feed is bookkeeping, jobs must finish."""
        try:
            _id = self.documents.insert_one(EVENTS_COLLECTION, {
                "artifact": artifact,
                "event": event,
                "artifactType": metadata.get("type"),
                "ts": time.time(),
            })
            if _id % 256 == 0:
                # Probabilistic pruning keeps the feed bounded without
                # a scan per insert.
                for old in self.documents.find(
                    EVENTS_COLLECTION,
                    query={"_id": {"$lt": _id - EVENT_RETAIN}},
                ):
                    self.documents.delete_one(
                        EVENTS_COLLECTION, old["_id"]
                    )
        except Exception:  # noqa: BLE001
            pass

    def latest_events(self, n: int = 20) -> list[dict]:
        """The NEWEST ``n`` events, oldest first — for dashboards.
        (``events()`` pages forward from a cursor; its cap would pin a
        long-lived server's view to the first 1000 records.)"""
        try:
            total = self.documents.count(EVENTS_COLLECTION)
            if not total:
                return []
            return self.documents.find(
                EVENTS_COLLECTION, skip=max(0, total - n), limit=n
            )
        except NoSuchCollection:
            return []

    def events(self, since_id: int = -1, limit: int = 100) -> list[dict]:
        """Events with ``_id > since_id``, oldest first, at most
        ``limit`` — poll with the last seen ``_id`` as the cursor.
        The default (-1) returns from the beginning: feed ids start
        at 0."""
        try:
            return self.documents.find(
                EVENTS_COLLECTION,
                query={"_id": {"$gt": int(since_id)}},
                limit=max(1, min(int(limit), 1000)),
            )
        except NoSuchCollection:
            return []  # no event ever recorded
