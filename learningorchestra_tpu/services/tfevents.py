"""Minimal TensorBoard event-file (tfevents) writer — no TF dependency.

The reference's managed TensorBoard shows live training curves because
keras writes event files into the monitored logdir (reference:
binary_executor_image/server.py:323-329 spawns ``tensorboard --logdir``;
the callbacks write the events).  Round 1 wrote CSVs, which TensorBoard
does not render (VERDICT r1 missing item 6); this module emits the real
record format so managed sessions display loss/accuracy scalars.

Format (TFRecord framing + two hand-encoded protos):

    record  := len:uint64le  masked_crc32c(len):uint32le
               data:bytes    masked_crc32c(data):uint32le
    data    := tensorflow.Event   (proto3)
      Event.wall_time    = field 1, double
      Event.step         = field 2, int64 varint
      Event.file_version = field 3, string   (first record only)
      Event.summary      = field 5, message Summary
      Summary.value      = field 1, repeated Summary.Value
      Value.tag          = field 1, string
      Value.simple_value = field 2, float32

CRC is crc32c (Castagnoli) with TFRecord's rotate-and-add mask.
Verified against TensorBoard's own ``event_pb2`` parser in
tests/test_tfevents.py.
"""

from __future__ import annotations

import os
import socket
import struct
import time

# -- crc32c (Castagnoli, table-driven) --------------------------------------

_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE:
        return _CRC_TABLE
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    _CRC_TABLE = table
    return table


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for byte in data:
        crc = table[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- minimal proto encoding --------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _pb_double(field: int, value: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", value)


def _pb_float(field: int, value: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", value)


def _pb_varint(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(value)


def _pb_bytes(field: int, value: bytes) -> bytes:
    return _key(field, 2) + _varint(len(value)) + value


def _scalar_event(wall_time: float, step: int, tag: str,
                  value: float) -> bytes:
    summary_value = _pb_bytes(1, tag.encode()) + _pb_float(2, value)
    summary = _pb_bytes(1, summary_value)
    return (
        _pb_double(1, wall_time)
        + _pb_varint(2, step)
        + _pb_bytes(5, summary)
    )


def _version_event(wall_time: float) -> bytes:
    return _pb_double(1, wall_time) + _pb_bytes(3, b"brain.Event:2")


def _record(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + data
        + struct.pack("<I", _masked_crc(data))
    )


# -- public API --------------------------------------------------------------


def write_scalars(
    logdir: str | os.PathLike,
    history: dict,
    *,
    prefix: str = "",
    wall_time: float | None = None,
) -> str:
    """Write a TrainHistory ({metric: [per-epoch values]}) as one
    tfevents file TensorBoard renders as scalar curves; returns the
    file path.  Tags are ``{prefix}/{metric}`` when a prefix is given.
    """
    os.makedirs(logdir, exist_ok=True)
    t0 = time.time() if wall_time is None else wall_time
    host = socket.gethostname() or "host"
    path = os.path.join(
        logdir, f"events.out.tfevents.{int(t0)}.{host}.{os.getpid()}"
    )
    with open(path, "wb") as fh:
        fh.write(_record(_version_event(t0)))
        n = max((len(v) for v in history.values()), default=0)
        for step in range(n):
            for metric in sorted(history):
                values = history[metric]
                if step >= len(values):
                    continue
                try:
                    value = float(values[step])
                except (TypeError, ValueError):
                    continue
                tag = f"{prefix}/{metric}" if prefix else metric
                fh.write(_record(
                    # Spread wall times so TB's relative-time axis works.
                    _scalar_event(t0 + step * 1e-3, step, tag, value)
                ))
    return path
