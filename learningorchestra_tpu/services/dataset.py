"""Dataset service: CSV and generic binary ingest + the universal GET path.

Reference behavior (microservices/database_api_image/): ``POST
/dataset/csv`` downloads a CSV from a URL and stores it row-per-document
with a 3-thread download→treat→save queue pipeline and **per-row
insert_one** — its known ingest bottleneck (database.py:86-151).  Here
ingest is a streamed reader with **batched** inserts; headers are cleaned
the same way (non-alphanumeric → underscore) and values optionally
type-inferred (the reference stores everything as strings and makes users
cast via the dataType service — that path still exists for parity, but
inference is the sane default).

``POST /dataset/generic`` streams arbitrary bytes onto the datasets
volume in chunks (database.py:61-83).
"""

from __future__ import annotations

import contextlib
import csv
import json
import math
import os
import re
from typing import Iterable

from learningorchestra_tpu.services.context import ServiceContext

_HEADER_CLEAN_RE = re.compile(r"[^0-9a-zA-Z_]+")

CSV_TYPE = "dataset/csv"
GENERIC_TYPE = "dataset/generic"
TENSOR_TYPE = "dataset/tensor"


def _clean_header(header: list[str]) -> list[str]:
    out = []
    for i, h in enumerate(header):
        h = _HEADER_CLEAN_RE.sub("_", h.strip()).strip("_")
        out.append(h or f"col{i}")
    return out


_INT_RE = re.compile(r"[+-]?[0-9]+")


def _infer(value: str):
    """Type inference matching the native CSV engine exactly (native/src/
    docstore.cpp infer_value): the two ingest paths must store identical
    values or the backends aren't interchangeable.  Deliberately stricter
    than Python's int()/float(): no '_' separators, no inf/nan spellings,
    no hex; ints beyond int64 degrade to float like strtoll/ERANGE."""
    v = value.strip()
    if v == "":
        # Whitespace-only counts as empty (NaN downstream) in BOTH
        # engines — the native parser trims the full whitespace set
        # before classifying, and a cell of spaces is "empty", not a
        # non-numeric string.
        return None
    if _INT_RE.fullmatch(v):
        iv = int(v)
        if -(2 ** 63) <= iv < 2 ** 63:
            return iv
        return float(v)
    if any(c in "_xX" for c in v):
        return value
    try:
        f = float(v)
    except ValueError:
        return value
    if math.isnan(f) or math.isinf(f):
        return value
    return f


def _decode_lines(byte_chunks):
    """Incrementally decode byte chunks into lines split ONLY on ``\\n``,
    terminators preserved.  ``str.splitlines`` semantics (which
    ``iter_lines(decode_unicode=True)`` uses) would split on \\x85/\\u2028
    and collapse \\r\\n — corrupting quoted CSV fields that contain them;
    ``csv.reader`` needs the raw terminators to parse multi-line quoted
    fields faithfully."""
    import codecs

    dec = codecs.getincrementaldecoder("utf-8")("replace")
    buf = ""
    for chunk in byte_chunks:
        buf += dec.decode(chunk)
        if "\n" in buf:
            parts = buf.split("\n")
            buf = parts.pop()
            for part in parts:
                yield part + "\n"
    buf += dec.decode(b"", True)
    if buf:
        yield buf


@contextlib.contextmanager
def _open_url(url: str):
    """Stream a CSV source as an iterable of text lines: http(s) URL,
    file:// URL, or local path.

    The HTTP path decodes raw chunks itself rather than wrapping
    ``resp.raw`` in a TextIOWrapper: urllib3 closes the underlying
    connection the moment the body hits EOF, after which the io wrapper's
    own buffering read raises "I/O operation on closed file".
    ``csv.reader`` accepts any iterable of strings, so no file object is
    needed.  Local files open with ``newline=""`` (csv-module contract) so
    \\r\\n inside quoted fields survives.
    """
    if url.startswith(("http://", "https://")):
        import requests

        resp = requests.get(url, stream=True, timeout=60)
        resp.raise_for_status()
        try:
            yield _decode_lines(resp.iter_content(chunk_size=65536))
        finally:
            resp.close()
    else:
        path = url[len("file://"):] if url.startswith("file://") else url
        with open(
            path, "r", encoding="utf-8", errors="replace", newline=""
        ) as fh:
            yield fh


@contextlib.contextmanager
def _open_url_bytes(url: str):
    """Stream a CSV source as an iterator of byte chunks (the native
    numeric parser consumes raw bytes; decoding per-line would cost the
    Python loop this path exists to skip)."""
    if url.startswith(("http://", "https://")):
        import requests

        resp = requests.get(url, stream=True, timeout=60)
        resp.raise_for_status()
        try:
            yield resp.iter_content(chunk_size=1 << 20)
        finally:
            resp.close()
    else:
        path = url[len("file://"):] if url.startswith("file://") else url
        with open(path, "rb") as fh:
            yield iter(lambda: fh.read(1 << 22), b"")


class DatasetService:
    BATCH = 2000  # rows per insert_many

    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    # -- CSV ------------------------------------------------------------------

    def create_csv(
        self, name: str, url: str, *, infer_types: bool = True,
        shard_rows: int | None = None,
    ) -> dict:
        """Async ingest: metadata appears immediately (finished=False),
        rows stream in on a job thread — the reference's ASYNC BOUNDARY
        (database.py:99-105).

        ``shard_rows`` switches to SHARDED ingest for beyond-host-RAM
        datasets: rows stream into columnar ``.npz`` shards on the
        volume (store/sharded.py) instead of store documents, with the
        first page of rows kept as store docs for GET preview parity.
        Training then streams the shards (train/neural.py
        ``_fit_streaming``) — the reference's any-size ingest+train
        contract (database.py:86-151) without ever materializing the
        dataset as one array."""
        self.ctx.require_new_name(name)
        meta = self.ctx.artifacts.metadata.create(
            name, CSV_TYPE, extra={"url": url}
        )

        def ingest():
            if shard_rows:
                return self._ingest_sharded(
                    name, url, int(shard_rows), infer_types
                )
            native = self._ingest_native(name, url, infer_types)
            if native is not None:
                return native
            n_rows = 0
            fields: list[str] = []
            with _open_url(url) as fh:
                reader = csv.reader(fh)
                batch: list[dict] = []
                for row in reader:
                    if not fields:
                        fields = _clean_header(row)
                        continue
                    if not row:
                        continue
                    doc = {
                        fields[i]: (_infer(v) if infer_types else v)
                        for i, v in enumerate(row[: len(fields)])
                    }
                    batch.append(doc)
                    if len(batch) >= self.BATCH:
                        self.ctx.documents.insert_many(name, batch)
                        n_rows += len(batch)
                        batch = []
                if batch:
                    self.ctx.documents.insert_many(name, batch)
                    n_rows += len(batch)
            return {"fields": fields, "rows": n_rows}

        self.ctx.engine.submit(
            name,
            ingest,
            description=f"csv ingest from {url}",
            on_success=lambda r: r,
            job_class="dataset",
        )
        return meta

    # Above this size the whole-buffer native path would hold ~2.5x the
    # file resident (download + JSONL + store copy); stream instead.
    NATIVE_MAX_BYTES = 256 * 1024 * 1024

    def _ingest_native(self, name: str, url: str, infer_types: bool):
        """Fully-native ingest: C++ CSV parse → C++ store insert, no
        per-row Python objects (vs. the reference's per-row insert_one,
        database_api_image/database.py:139-151).  Returns None (before
        touching the store) when the native engine is unavailable, the
        file is too big to buffer, or the parse fails — the streaming
        Python path then takes over."""
        try:
            from learningorchestra_tpu import native

            if not native.native_available():
                return None
            if url.startswith(("http://", "https://")):
                import requests

                # Stream with a byte cap — Content-Length may be absent
                # (chunked responses), so the guard must be on actual
                # bytes received; when the header IS present, bail before
                # downloading anything (the streaming fallback would have
                # to re-download whatever we buffered here).
                resp = requests.get(url, stream=True, timeout=60)
                resp.raise_for_status()
                declared = int(resp.headers.get("content-length") or 0)
                if declared > self.NATIVE_MAX_BYTES:
                    resp.close()
                    return None
                chunks, total = [], 0
                for chunk in resp.iter_content(chunk_size=1 << 20):
                    total += len(chunk)
                    if total > self.NATIVE_MAX_BYTES:
                        resp.close()
                        return None  # too big to buffer: stream instead
                    chunks.append(chunk)
                data = b"".join(chunks)
            else:
                path = url[len("file://"):] if url.startswith("file://") \
                    else url
                if os.path.getsize(path) > self.NATIVE_MAX_BYTES:
                    return None
                with open(path, "rb") as fh:
                    data = fh.read()
            # Normalize to valid UTF-8 the way the streaming path's
            # errors="replace" decoder does — the store holds JSON text.
            try:
                data.decode("utf-8")
            except UnicodeDecodeError:
                data = data.decode("utf-8", errors="replace").encode("utf-8")
            fields, jsonl = native.csv_parse(data, infer_types)
        except Exception:
            return None  # nothing inserted yet — safe to re-ingest
        if hasattr(self.ctx.documents, "insert_jsonl"):
            n = self.ctx.documents.insert_jsonl(name, jsonl)
        else:
            n = self.ctx.documents.insert_many(
                name, (json.loads(ln) for ln in jsonl.splitlines() if ln)
            )
        return {"fields": fields, "rows": n}

    PREVIEW_ROWS = 100  # GET page cap (constants.py:42-44) = preview size

    def _ingest_sharded(
        self, name: str, url: str, shard_rows: int, infer_types: bool
    ) -> dict:
        """Stream CSV rows into columnar volume shards.

        Peak host memory is O(shard_rows · n_cols), whatever the file
        size.  The first PREVIEW_ROWS rows also land in the document
        store so ``GET /dataset/csv/<name>`` pages work unchanged (the
        full row set deliberately does NOT — a beyond-RAM dataset as
        row documents is the bottleneck this path exists to avoid).
        Columns must be numeric (empty cells → NaN; integer columns
        with gaps promote to float): training is the only consumer of
        shards, and it needs matrices, not strings.
        """
        from learningorchestra_tpu.store.sharded import (
            ShardedDatasetWriter,
        )

        root = self.ctx.volumes.path_for(CSV_TYPE, name)
        if infer_types:
            native_result = self._ingest_sharded_native(
                name, root, url, shard_rows
            )
            if native_result is not None:
                return native_result
        writer = None
        preview: list[dict] = []
        fields: list[str] = []
        n_rows = 0
        with _open_url(url) as fh:
            for row in csv.reader(fh):
                if not fields:
                    fields = _clean_header(row)
                    writer = ShardedDatasetWriter(
                        root, fields, rows_per_shard=shard_rows
                    )
                    continue
                if not row:
                    continue
                vals = [
                    _infer(v) if infer_types else v
                    for v in row[: len(fields)]
                ]
                vals += [None] * (len(fields) - len(vals))
                numeric = [
                    float("nan") if v is None else v for v in vals
                ]
                writer.append(numeric)
                if len(preview) < self.PREVIEW_ROWS:
                    preview.append(dict(zip(fields, vals)))
                n_rows += 1
        if writer is None:
            raise ValueError(f"CSV at {url} has no header row")
        manifest = writer.close()
        if preview:
            self.ctx.documents.insert_many(name, preview)
        return {
            "fields": fields,
            "rows": n_rows,
            "sharded": True,
            "shards": len(manifest["shard_rows"]),
            "shardRows": shard_rows,
            "previewRows": len(preview),
        }

    _NATIVE_CHUNK = 4 << 20  # bytes fed to the native parser per call

    def _ingest_sharded_native(
        self, name: str, root, url: str, shard_rows: int
    ) -> dict | None:
        """Native-engine sharded ingest: raw bytes → C++ quote-aware
        CSV records → packed float64 blocks → columnar shards, no
        per-row (or per-cell) Python objects on the hot path.  Returns
        None when the native library is unavailable (the Python loop
        above is the fallback, same contract).  Parity notes: short
        rows pad NaN, empty cells are NaN, a column with any non-empty
        unparseable cell fails the job exactly like the row path's
        "column is not numeric", and dtype inference is FORMAT-based in
        both paths — the parser reports per-column float-formatted-cell
        counts, so "5.0" stays float32 here exactly as ``_infer`` keeps
        it in the row path (a model's loss selection must not depend on
        which ingest engine ran — ADVICE r3).
        """
        try:
            from learningorchestra_tpu import native

            if not native.native_available():
                return None
        except Exception:  # noqa: BLE001 — fallback, not failure
            return None
        import numpy as np

        from learningorchestra_tpu.store.sharded import (
            ShardedDatasetWriter,
        )

        writer = None
        fields: list[str] = []
        bad = None
        n_rows = 0
        head_bytes = b""  # first bytes kept for the text preview
        buf = b""
        with _open_url_bytes(url) as chunks:
            it = iter(chunks)
            final = False
            while True:
                if not final:
                    piece = next(it, None)
                    if piece is None:
                        final = True
                    else:
                        buf += piece
                        if len(head_bytes) < (1 << 18):
                            # Captured from the PIECES in stream order
                            # (buf shrinks as records consume — slicing
                            # it later would caption mid-file bytes as
                            # the head).
                            head_bytes += piece[
                                : (1 << 18) - len(head_bytes)
                            ]
                if not fields:
                    nl = buf.find(b"\n")
                    if nl < 0:
                        if not final:
                            continue
                        if not buf.strip():
                            raise ValueError(
                                f"CSV at {url} has no header row"
                            )
                        nl = len(buf)
                    header_line = buf[:nl].lstrip(
                        b"\xef\xbb\xbf"
                    ).decode("utf-8", "replace").rstrip("\r")
                    fields = _clean_header(
                        next(csv.reader([header_line]))
                    )
                    writer = ShardedDatasetWriter(
                        root, fields, rows_per_shard=shard_rows
                    )
                    bad = np.zeros(len(fields), np.int64)
                    ffmt = np.zeros(len(fields), np.int64)
                    buf = buf[nl + 1:]
                while len(buf) >= self._NATIVE_CHUNK or (final and buf):
                    block, consumed = native.csv_numeric_chunk(
                        buf, len(fields), is_final=final,
                        bad_counts=bad, float_counts=ffmt,
                    )
                    if consumed == 0:
                        # One record longer than the buffer: read more.
                        break
                    if len(block):
                        writer.append_block(
                            block, float_format_cols=ffmt > 0
                        )
                        n_rows += len(block)
                    buf = buf[consumed:]
                if final and not buf:
                    break
        if writer is None:
            raise ValueError(f"CSV at {url} has no header row")
        for i, count in enumerate(bad):
            if count:
                raise ValueError(
                    f"column {fields[i]!r} is not numeric "
                    f"({int(count)} unparseable cell(s)); cast or "
                    "project it away before sharded ingest"
                )
        manifest = writer.close()
        # Text preview from the retained head bytes — same shape the
        # Python path stores (typed values via _infer, strings kept).
        preview: list[dict] = []
        head_text = head_bytes.decode("utf-8", "replace")
        head_lines = head_text.splitlines()
        if len(head_bytes) >= (1 << 18) and not head_text.endswith("\n"):
            # The capture cap can cut mid-record; a truncated line
            # would preview silently wrong values.
            head_lines = head_lines[:-1]
        for row in csv.reader(head_lines[1:]):
            if len(preview) >= self.PREVIEW_ROWS or len(
                preview
            ) >= n_rows:
                break
            if not row:
                continue
            vals = [_infer(v) for v in row[: len(fields)]]
            vals += [None] * (len(fields) - len(vals))
            preview.append(dict(zip(fields, vals)))
        if preview:
            self.ctx.documents.insert_many(name, preview)
        return {
            "fields": fields,
            "rows": n_rows,
            "sharded": True,
            "shards": len(manifest["shard_rows"]),
            "shardRows": shard_rows,
            "previewRows": len(preview),
            "engine": "native",
        }

    # -- tensor (N-D, image-shaped) -------------------------------------------

    TENSOR_CHUNK_ROWS = 1024  # rows moved per mmap slice during ingest

    def create_tensor(
        self, name: str, url: str, *, labels_url: str,
        shard_rows: int = 4096,
    ) -> dict:
        """Sharded ingest of N-D features (the image-dataset shape —
        BASELINE config 5's ResNet/ImageNet, where a row is a (H, W, C)
        block a CSV cannot sanely carry).  ``url``/``labels_url`` point
        at ``.npy`` arrays; the source is memory-mapped and copied
        shard by shard, so host memory stays O(chunk) whatever the
        file size — the beyond-RAM contract of the CSV path
        (database_api_image/database.py:86-151), for tensors.

        The artifact trains exactly like a sharded CSV:
        ``x="$name"`` (or ``"$name.x"``), ``y="$name.label"``.
        """
        self.ctx.require_new_name(name)
        if int(shard_rows) <= 0:
            raise ValueError("shardRows must be a positive integer")
        meta = self.ctx.artifacts.metadata.create(
            name, TENSOR_TYPE,
            extra={"url": url, "labelsUrl": labels_url},
        )

        def ingest():
            import numpy as np

            from learningorchestra_tpu.store.sharded import (
                ShardedTensorWriter,
            )

            feats = np.load(self._local_npy(url), mmap_mode="r")
            labels = np.load(self._local_npy(labels_url), mmap_mode="r")
            if feats.ndim < 2:
                raise ValueError(
                    f"features must be (rows, ...), got {feats.shape}"
                )
            if labels.shape[0] != feats.shape[0] or labels.ndim != 1:
                raise ValueError(
                    f"labels must be ({feats.shape[0]},), got "
                    f"{labels.shape}"
                )
            root = self.ctx.volumes.path_for(TENSOR_TYPE, name)
            writer = ShardedTensorWriter(
                root,
                {"x": feats.shape[1:], "label": ()},
                rows_per_shard=int(shard_rows),
            )
            n = feats.shape[0]
            step = self.TENSOR_CHUNK_ROWS
            for i in range(0, n, step):
                writer.append_rows({
                    "x": np.asarray(feats[i:i + step]),
                    "label": np.asarray(labels[i:i + step]),
                })
            manifest = writer.close()
            return {
                "fields": ["x", "label"],
                "rows": n,
                "sharded": True,
                "shards": len(manifest["shard_rows"]),
                "shardRows": int(shard_rows),
                "featureShape": list(feats.shape[1:]),
            }

        self.ctx.engine.submit(
            name,
            ingest,
            description=f"tensor ingest from {url}",
            on_success=lambda r: r,
            job_class="dataset",
        )
        return meta

    def _local_npy(self, url: str) -> str:
        """A local filesystem path for an .npy source — downloads HTTP
        sources to the datasets volume first (streamed to disk) so
        ``np.load(mmap_mode='r')`` can map them."""
        if url.startswith(("http://", "https://")):
            import hashlib

            import requests

            cache_name = "npycache_" + hashlib.sha1(
                url.encode()
            ).hexdigest()[:16]
            resp = requests.get(url, stream=True, timeout=60)
            resp.raise_for_status()
            path = self.ctx.volumes.save_stream(
                GENERIC_TYPE, cache_name, resp.raw
            )
            return str(path)
        return url[len("file://"):] if url.startswith("file://") else url

    # -- generic binary -------------------------------------------------------

    def create_generic(self, name: str, url: str) -> dict:
        self.ctx.require_new_name(name)
        meta = self.ctx.artifacts.metadata.create(
            name, GENERIC_TYPE, extra={"url": url}
        )

        def ingest():
            if url.startswith(("http://", "https://")):
                import requests

                resp = requests.get(url, stream=True, timeout=60)
                resp.raise_for_status()
                path = self.ctx.volumes.save_stream(
                    GENERIC_TYPE, name, resp.raw
                )
            else:
                src = url[len("file://"):] if url.startswith("file://") \
                    else url
                with open(src, "rb") as fh:
                    path = self.ctx.volumes.save_stream(GENERIC_TYPE, name, fh)
            return {"sizeBytes": path.stat().st_size}

        self.ctx.engine.submit(
            name,
            ingest,
            description=f"generic ingest from {url}",
            on_success=lambda r: r,
            job_class="dataset",
        )
        return meta

    # -- ingest from rows (in-process path for clients/tests/benches) ---------

    def create_from_rows(
        self, name: str, rows: Iterable[dict], fields: list[str] | None = None
    ) -> dict:
        self.ctx.require_new_name(name)
        self.ctx.artifacts.metadata.create(name, CSV_TYPE)
        n = self.ctx.documents.insert_many(name, rows)
        first = self.ctx.documents.find_one(name, 1) or {}
        fields = fields or [k for k in first if k != "_id"]
        self.ctx.artifacts.metadata.mark_finished(
            name, {"fields": fields, "rows": n}
        )
        return self.ctx.artifacts.metadata.read(name)

    # -- read / list / delete -------------------------------------------------

    def read_page(
        self, name: str, query: dict | None = None, skip: int = 0,
        limit: int = 20,
    ) -> list[dict]:
        self.ctx.require_existing(name)
        cap = self.ctx.config.api.page_limit_max
        return self.ctx.artifacts.read_page(
            name, query=query, skip=skip, limit=min(limit, cap)
        )

    def list_metadata(self, type_prefix: str = "") -> list[dict]:
        return self.ctx.artifacts.list_by_type(type_prefix)

    def delete(self, name: str) -> None:
        self.ctx.delete_artifact(name)
