"""Model service: instantiate a registry class and persist the instance.

Reference parity (microservices/model_image/model.py:92-162): POST gives
``{name, modulePath, class, classParameters}``; the service validates the
module/class/params, instantiates **inside the async job** (pre-trained
nets may download weights there), and persists the instance.  PATCH
re-instantiates with new params; DELETE removes collection + binary.
"""

from __future__ import annotations

from learningorchestra_tpu import dsl
from learningorchestra_tpu.services.context import (
    ServiceContext,
    ValidationError,
)
from learningorchestra_tpu.toolkit import registry


class ModelService:
    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    def _validate(self, module_path, class_name, class_parameters):
        factory = registry.resolve(module_path, class_name)  # RegistryError
        bad = registry.validate_init_params(
            module_path, class_name, class_parameters or {}
        )
        if bad:
            raise ValidationError(f"invalid classParameters: {bad}")
        return factory

    def create(
        self,
        name: str,
        *,
        module_path: str,
        class_name: str,
        class_parameters: dict | None = None,
        artifact_type: str = "model/tensorflow",
        description: str = "",
    ) -> dict:
        self.ctx.require_new_name(name)
        factory = self._validate(module_path, class_name, class_parameters)
        meta = self.ctx.artifacts.metadata.create(
            name,
            artifact_type,
            module_path=module_path,
            class_name=class_name,
        )
        self._submit(name, factory, class_parameters, artifact_type,
                     description)
        return meta

    def update(
        self,
        name: str,
        *,
        class_parameters: dict | None = None,
        description: str = "",
    ) -> dict:
        """PATCH: re-instantiate with new parameters (reference:
        model_image/model.py:117-136)."""
        meta = self.ctx.require_existing(name)
        factory = self._validate(
            meta.get("modulePath"), meta.get("class"), class_parameters
        )
        self.ctx.artifacts.metadata.restart(name)
        self._submit(
            name, factory, class_parameters, meta.get("type"), description
        )
        return self.ctx.artifacts.metadata.read(name)

    def _submit(self, name, factory, class_parameters, artifact_type,
                description):
        def run():
            params = dsl.resolve_params(class_parameters, self.ctx.loader)
            instance = factory(**params)
            self.ctx.volumes.save_object(artifact_type, name, instance)
            return instance

        self.ctx.engine.submit(
            name, run, description=description or f"instantiate {name}",
            parameters=class_parameters,
            job_class="model",
        )

    def delete(self, name: str) -> None:
        self.ctx.delete_artifact(name)
