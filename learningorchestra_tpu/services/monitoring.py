"""Monitoring service — managed TensorBoard/XProf sessions + URL registry.

The reference spawns ``tensorboard --logdir <path>`` as a subprocess when a
train request carries ``monitoringPath``, scrapes the port from its stdout,
builds a public URL, returns it in ``extra_results`` and serves later
lookups by nickname (reference: microservices/binary_executor_image/
server.py:323-329 spawn, utils.py:358-399 URL discovery,
server.py:185-200 GET lookup).

TPU-native differences:
- sessions live in a supervised registry with atomic nickname allocation
  (the reference's collision handling was broken — SURVEY §5.2);
- a session's logdir also receives **JAX profiler traces**
  (``jax.profiler.trace``): per-job XLA/TPU timelines viewable in
  TensorBoard's profile plugin — the reference could only show what keras
  callbacks wrote;
- TensorBoard itself is optional: when the binary is absent the session
  still registers (logdir + trace capture work; ``url`` is None).
"""

from __future__ import annotations

import contextlib
import os
import re
import shutil
import socket
import subprocess
import threading
import time
from typing import Any

from learningorchestra_tpu.concurrency_rt import make_lock

_PORT_RE = re.compile(r"http://[^\s:]+:(\d+)")
# First char alphanumeric/underscore: forbids '.', '..' and path escapes.
_NICK_RE = re.compile(r"[A-Za-z0-9_][A-Za-z0-9_.\-]*")
# Fixed API sub-routes under /monitoring/<tool>/ (compiled-program
# cache counters, serving stats): a session so named could be created
# but never read back — its GET is shadowed.
_RESERVED_NICKNAMES = frozenset(
    {"compileCache", "compile_cache", "serving"}
)


class MonitoringError(Exception):
    pass


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class MonitoringSession:
    def __init__(self, nickname: str, logdir: str):
        self.nickname = nickname
        self.logdir = logdir
        self.url: str | None = None
        self.port: int | None = None
        self.process: subprocess.Popen | None = None
        self.stopped = False  # set by stop(); guards the spawn race
        self.created = time.time()

    def to_dict(self) -> dict:
        return {
            "nickname": self.nickname,
            "logdir": self.logdir,
            "url": self.url,
            "port": self.port,
            "running": self.process is not None
            and self.process.poll() is None,
        }


class MonitoringService:
    """Supervised registry of monitoring sessions, nickname → session."""

    def __init__(self, root: str, *, host: str = "127.0.0.1",
                 external_host: str | None = None):
        """``host`` is where TensorBoard binds; ``external_host``, when
        set, is the address ADVERTISED in session URLs — the reference
        advertises the box's external IP so remote clients can open
        them (binary_executor_image/utils.py:358-361).  Advertising an
        external address forces a 0.0.0.0 bind (the URL must resolve to
        a listening interface on a multi-homed k8s node)."""
        self.root = root
        self.host = "0.0.0.0" if external_host else host
        self.external_host = external_host
        self._sessions: dict[str, MonitoringSession] = {}
        self._lock = make_lock("MonitoringService._lock")

    # -- session lifecycle ---------------------------------------------------

    @staticmethod
    def valid_nickname(nickname: str) -> bool:
        return bool(_NICK_RE.fullmatch(nickname or "")) \
            and nickname not in _RESERVED_NICKNAMES

    def start(self, nickname: str, *, spawn_tensorboard: bool = True) -> dict:
        """Create (or return) the session for ``nickname``.

        Atomic: concurrent starts for the same nickname return the same
        session instead of racing two TensorBoard processes onto one
        logdir (the reference's ProcessController collision path raised —
        utils.py:366)."""
        if not self.valid_nickname(nickname):
            # Nicknames become directory names under root ('..' or
            # separators would escape the monitoring tree), and the
            # reserved names are fixed API sub-routes a session could
            # never be read back from.
            raise MonitoringError(f"invalid monitoring nickname {nickname!r}")
        with self._lock:
            existing = self._sessions.get(nickname)
            if existing is not None:
                return existing.to_dict()
            logdir = os.path.join(self.root, nickname)
            os.makedirs(logdir, exist_ok=True)
            session = MonitoringSession(nickname, logdir)
            self._sessions[nickname] = session
        if spawn_tensorboard:
            self._spawn_tensorboard(session)
        return session.to_dict()

    def _spawn_tensorboard(self, session: MonitoringSession) -> None:
        binary = shutil.which("tensorboard")
        if binary is None:
            return  # logdir-only session; traces still collect
        port = _free_port()
        try:
            # DEVNULL: nothing reads the child's output, and a PIPE nobody
            # drains would block TensorBoard once the OS buffer fills.
            cmd = [binary, "--logdir", session.logdir, "--port", str(port)]
            # Local mode binds loopback only.  With external_host set
            # the advertised URL must resolve to a listening interface,
            # so TB binds all — the reference's exact posture (it
            # advertises the box's external IP, utils.py:358-361, with
            # no auth); restrict reachability with a NetworkPolicy /
            # firewall at the deploy layer, not here.
            cmd += ["--host", self.host] if self.host != "0.0.0.0" \
                else ["--bind_all"]
            proc = subprocess.Popen(
                cmd,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT,
            )
        except OSError:
            return
        doomed = None
        with self._lock:
            if session.stopped:
                # stop() won the race before the process existed.
                doomed = proc
            else:
                session.process = proc
                session.port = port
        if doomed is not None:
            # Terminate AND reap outside the lock — terminate() alone
            # would leave a zombie for the server's lifetime.
            doomed.terminate()
            try:
                doomed.wait(timeout=10)
            except subprocess.TimeoutExpired:
                doomed.kill()
                doomed.wait()
            return

        # Probe for readiness off-thread: the caller is an HTTP POST
        # handler and must not stall on TensorBoard startup; ``url`` stays
        # None until the server answers (lookup tolerates None).
        def probe_ready():
            # Probe locally (a 0.0.0.0 bind answers on loopback), but
            # advertise the external host when one is configured.
            probe_host = "127.0.0.1" if self.host == "0.0.0.0" else self.host
            deadline = time.time() + 30
            while time.time() < deadline:
                if proc.poll() is not None:
                    return  # died; stay logdir-only
                with socket.socket() as probe:
                    probe.settimeout(0.2)
                    if probe.connect_ex((probe_host, port)) == 0:
                        # Re-check under the lock: stop() may have
                        # popped the session (and terminated the
                        # process) while this probe was connecting — a
                        # stopped session must never advertise a live
                        # TensorBoard address to a concurrent lookup
                        # holding the same session object.
                        with self._lock:
                            if not session.stopped:
                                session.url = self.advertised_url(port)
                        return
                time.sleep(0.2)

        threading.Thread(target=probe_ready, daemon=True).start()

    def advertised_url(self, port: int) -> str:
        """The URL written into a ready session: external host when
        configured (reference: utils.py:358-361 builds it from the
        box's external IP), bind host otherwise."""
        return f"http://{self.external_host or self.host}:{port}/"

    def lookup(self, nickname: str) -> dict:
        """GET by nickname (reference: server.py:185-200)."""
        with self._lock:
            session = self._sessions.get(nickname)
        if session is None:
            raise MonitoringError(f"no monitoring session {nickname!r}")
        return session.to_dict()

    def list_sessions(self) -> list[dict]:
        with self._lock:
            return [s.to_dict() for s in self._sessions.values()]

    @staticmethod
    def compile_cache_stats() -> dict:
        """Process-wide compiled-program cache counters
        (train/compile_cache.py) — served at
        GET /monitoring/<tool>/compileCache so cache effectiveness
        (hit/miss/eviction/trace-time) is observable without shell
        access, alongside the per-job deltas the executor stamps into
        finished-job metadata.  Each resident entry's byte charge
        (measured vs fallback) rides in ``entries_detail``; the
        per-program FLOPs/HBM records join in under ``programCosts``
        (obs/costs.py); the durable AOT executable store's counters
        (train/aot_store.py — zeros when disabled) under ``aot``."""
        from learningorchestra_tpu.train import compile_cache

        stats = compile_cache.get_cache().stats()
        try:
            from learningorchestra_tpu.obs import costs as obs_costs

            if obs_costs.enabled():
                stats["programCosts"] = (
                    obs_costs.get_ledger().snapshot()
                )
        except Exception:  # noqa: BLE001 — cost listing must never
            pass  # fail the monitoring poll
        try:
            from learningorchestra_tpu.train import aot_store

            stats["aot"] = aot_store.stats_snapshot()
        except Exception:  # noqa: BLE001 — same contract as above
            pass
        return stats

    def stop(self, nickname: str) -> bool:
        with self._lock:
            session = self._sessions.pop(nickname, None)
            if session is not None:
                session.stopped = True
        if session is None:
            return False
        if session.process is not None and session.process.poll() is None:
            session.process.terminate()
            try:
                session.process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                session.process.kill()
        return True

    def close(self) -> None:
        for nickname in list(self._sessions):
            self.stop(nickname)

    # -- JAX profiler traces --------------------------------------------------

    @contextlib.contextmanager
    def trace(self, nickname: str):
        """Capture a JAX profiler trace into the session's logdir.

        Wrap a train loop: the resulting XPlane shows XLA op timelines,
        HBM usage and (on TPU) MXU utilization in TensorBoard's profile
        tab — per-job, the way the reference registered per-job
        TensorBoard monitors."""
        info = self.start(nickname, spawn_tensorboard=False)
        import jax

        try:
            jax.profiler.start_trace(info["logdir"])
            started = True
        except Exception:
            started = False  # another trace already active — skip, not fail
        try:
            yield info
        finally:
            if started:
                with contextlib.suppress(Exception):
                    jax.profiler.stop_trace()


def write_scalar_logs(logdir: str, history: dict, *, prefix: str = "") -> int:
    """Write a TrainHistory into the monitored logdir twice over:

    - a real tfevents file (services/tfevents.py) so the managed
      TensorBoard session renders loss/accuracy curves — the reference's
      monitoring contract (binary_executor_image/server.py:323-329,
      where keras callbacks write the events);
    - a CSV as the human-readable copy.

    Durable metrics rows for the GET/poll contract live in the document
    store (SURVEY §5.5).  Returns the epoch-row count."""
    from learningorchestra_tpu.services.tfevents import write_scalars

    os.makedirs(logdir, exist_ok=True)
    write_scalars(logdir, history, prefix=prefix)
    path = os.path.join(logdir, f"{prefix or 'metrics'}.csv")
    keys = sorted(history)
    n = max((len(v) for v in history.values()), default=0)
    with open(path, "w") as fh:
        fh.write(",".join(["step"] + keys) + "\n")
        for i in range(n):
            row = [str(i)] + [
                str(history[k][i]) if i < len(history[k]) else ""
                for k in keys
            ]
            fh.write(",".join(row) + "\n")
    return n
