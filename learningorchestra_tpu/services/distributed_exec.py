"""Distributed execution service — the reference's flagship paths.

Covers two routes (SURVEY §2.2, §3.3):

- ``POST /train/horovod`` (reference: binary_executor_image/
  binary_execution.py:237-292 — ship model JSON to Ray workers, Horovod
  ring-allreduce inside ``model.fit``, rank-0 weights home): here the
  same request shape drives :class:`DistributedTrainer` — one jitted
  train step over a named mesh, gradients psum'd over ICI by XLA's SPMD
  partitioner; no model serialization, no host ring, no weight lists.

- ``POST /builder/tensorflow|pytorch`` (reference:
  binary_execution.py:295-348 — ast-validate a single user function,
  compile, run on every Ray worker): here the validated function runs
  once per rank with ``rank``/``world_size`` kwargs — locally on
  threads, or fanned over per-host agents when a coordinator is
  configured (parallel/coordinator.py) — and per-rank results persist as
  result rows + a dill binary.

Request parity: ``training_parameters`` split into per-rank ``callbacks``
vs ``rank0callbacks`` survives as a declarative passthrough; the
``compile_code`` escape hatch maps to the declarative ``compile`` spec
(optimizer/loss via the ``#`` DSL) rather than exec'd source.
"""

from __future__ import annotations

import ast
import concurrent.futures
import time

from learningorchestra_tpu import dsl
from learningorchestra_tpu.services.context import (
    ServiceContext,
    ValidationError,
)
from learningorchestra_tpu.services.executor import (
    ExecutorService,
    _json_safe,
    store_history_rows,
)
from learningorchestra_tpu.services.monitoring import (
    MonitoringService,
    write_scalar_logs,
)

DISTRIBUTED_TRAIN_TYPE = "train/tensorflow"
DISTRIBUTED_BUILDER_TYPE = "builder/horovod"
# One request must not be able to exhaust the server's threads: ranks are
# host threads here (the compute inside each is XLA's concern).
MAX_BUILDER_WORKERS = 256


def _validate_single_function(code: str) -> str:
    """The builder contract: the payload is EXACTLY one top-level function
    definition (reference ast-validates this, binary_execution.py:328-339).
    Returns the function name."""
    try:
        tree = ast.parse(code)
    except SyntaxError as exc:
        raise ValidationError(f"function does not parse: {exc}") from exc
    if any(isinstance(n, ast.AsyncFunctionDef) for n in tree.body):
        # run() calls the function synchronously per rank; an async def
        # would return an un-awaitable coroutine instead of results.
        raise ValidationError("builder function must not be async")
    defs = [n for n in tree.body if isinstance(n, ast.FunctionDef)]

    def allowed(node: ast.stmt) -> bool:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            return True
        # Expr is only a docstring — a bare call would execute at module
        # exec time, outside the per-rank function the contract promises.
        return isinstance(node, ast.Expr) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str)

    others = [n for n in tree.body if n not in defs and not allowed(n)]
    if len(defs) != 1 or others:
        raise ValidationError(
            "builder function must be a single top-level function "
            "definition (imports and a docstring are allowed)"
        )
    return defs[0].name


class DistributedExecutorService:
    def __init__(self, ctx: ServiceContext,
                 monitoring: MonitoringService | None = None):
        self.ctx = ctx
        self.monitoring = monitoring

    # -- distributed training -------------------------------------------------

    def create_train(
        self,
        name: str,
        *,
        parent_name: str,
        training_parameters: dict | None = None,
        compile_spec: dict | None = None,
        mesh: dict | None = None,
        monitoring_path: str | None = None,
        artifact_type: str = DISTRIBUTED_TRAIN_TYPE,
        description: str = "",
    ) -> tuple[dict, dict]:
        """Returns (metadata, extra_results) — extra carries the
        monitoring URL the reference returned inline
        (server.py:70-76,104)."""
        self.ctx.require_new_name(name)
        ExecutorService._reject_raw_checkpoint_dir(training_parameters)
        parent_meta = self.ctx.require_finished_parent(parent_name)
        # Resolve + validate the monitoring nickname BEFORE creating the
        # artifact: a bad monitoringPath must 406, not burn the name on a
        # metadata doc whose job never got submitted.
        session_name = None
        if monitoring_path is not None and self.monitoring is not None:
            session_name = str(monitoring_path).strip("/").replace(
                "/", "_"
            ) or name
            if not self.monitoring.valid_nickname(session_name):
                raise ValidationError(
                    f"invalid monitoringPath {monitoring_path!r}"
                )
        model_meta = self.ctx.artifacts.metadata.find_model_ancestor(
            parent_name
        )
        meta = self.ctx.artifacts.metadata.create(
            name,
            artifact_type,
            parent_name=parent_name,
            module_path=model_meta.get("modulePath"),
            class_name=model_meta.get("class"),
            method="fit",
            extra={"distributed": True, "mesh": _json_safe(mesh or {})},
        )

        extra_results: dict = {}
        session_logdir = None
        if session_name is not None:
            session_info = self.monitoring.start(session_name)
            # Capture the logdir now: a mid-train DELETE of the session
            # must not fail an otherwise-successful training job.
            session_logdir = session_info["logdir"]
            extra_results["monitoring"] = session_info

        self._submit_train(
            name, parent_meta, training_parameters, compile_spec, mesh,
            artifact_type, description,
            session_name=session_name, session_logdir=session_logdir,
            resume_default=False,
        )
        return meta, extra_results

    def update_train(
        self,
        name: str,
        *,
        training_parameters: dict | None = None,
        compile_spec: dict | None = None,
        mesh: dict | None = None,
        description: str = "",
    ) -> dict:
        """PATCH re-run.  A FAILED (e.g. preempted) distributed job
        resumes from its managed in-loop checkpoint; re-running a
        finished job starts fresh so new parameters apply — identical
        semantics to the single-device executor's PATCH."""
        meta = self.ctx.require_existing(name)
        ExecutorService._reject_raw_checkpoint_dir(training_parameters)
        parent = meta.get("parentName")
        if not parent:
            raise ValidationError(
                f"artifact {name!r} has no parent — not a train result"
            )
        parent_meta = self.ctx.require_finished_parent(parent)
        resume = meta.get("jobState") == "failed"
        if not training_parameters:
            # Bare PATCH ("just resume"): re-run with the original
            # request's parameters from the execution ledger rather than
            # reaching fit() with no x/y (ADVICE r1).
            training_parameters = self.ctx.last_recorded_parameters(name)
        self.ctx.artifacts.metadata.restart(name)
        self._submit_train(
            name, parent_meta, training_parameters, compile_spec,
            mesh or meta.get("mesh"), meta.get("type"), description,
            session_name=None, session_logdir=None,
            resume_default=resume,
        )
        return self.ctx.artifacts.metadata.read(name)

    def _submit_train(
        self, name, parent_meta, training_parameters, compile_spec, mesh,
        artifact_type, description, *, session_name, session_logdir,
        resume_default,
    ):
        parent_name = parent_meta["name"]
        parent_type = parent_meta.get("type", "")

        if self.ctx.config.dist.task_coordinator:
            return self._submit_train_cluster(
                name, parent_name, parent_type, training_parameters,
                compile_spec, mesh, artifact_type, description,
                resume_default=resume_default,
                session_logdir=session_logdir,
            )

        def run():
            from learningorchestra_tpu.parallel.distributed import (
                DistributedTrainer,
            )
            from learningorchestra_tpu.parallel.mesh import MeshSpec
            from learningorchestra_tpu.train import compile_cache

            cache_before = compile_cache.counters_snapshot()
            instance = self.ctx.volumes.read_object(parent_type, parent_name)
            if not hasattr(instance, "module"):
                raise ValidationError(
                    f"parent {parent_name!r} is not a neural estimator — "
                    f"distributed training requires one"
                )
            params = dsl.resolve_params(
                training_parameters, self.ctx.loader
            )
            if compile_spec:
                instance.compile(
                    **dsl.resolve_params(compile_spec, self.ctx.loader)
                )
            spec = MeshSpec.from_dict(mesh) if mesh else None
            # shard_sequence=None → trainer auto-default (on iff sp>1);
            # the mesh body can force it with "shardSequence".
            shard_seq = (mesh or {}).get("shardSequence")
            trainer = DistributedTrainer(
                instance, spec=spec,
                shard_sequence=None if shard_seq is None
                else bool(shard_seq),
            )
            # Managed in-loop checkpoints (train/checkpoint.py).  The
            # directory is always the managed one — raw paths were
            # rejected at the route.  resume defaults by request kind:
            # fresh POST wipes stale state; PATCH of a failed job
            # resumes it; an in-engine preemption RETRY (attempt > 0)
            # always resumes — its checkpoints are this run's own
            # state, never stale (the PR-7 current_attempt threading
            # the single-device path already has).
            import shutil as _shutil

            from learningorchestra_tpu.jobs import (
                engine as engine_mod,
            )

            attempt = engine_mod.current_attempt()
            ckdir = self.ctx.checkpoint_dir(name)
            params.setdefault("resume", resume_default)
            if attempt > 0:
                # A retry's checkpoints are this run's own state —
                # resume even when the request said fresh-fit.
                params["resume"] = True
            if not params["resume"] and ckdir.exists():
                _shutil.rmtree(ckdir, ignore_errors=True)
            params["checkpoint_dir"] = str(ckdir)
            # A distributed fit spans the host's whole slice: lease ALL
            # devices so it never interleaves with single-chip jobs.
            with self.ctx.leaser.lease(0, label=name) as devs:
                if devs:
                    self.ctx.artifacts.metadata.update(
                        name, {"leasedDevices": devs}
                    )
                from learningorchestra_tpu.obs import (
                    tracing as obs_tracing,
                )

                t0 = time.perf_counter()
                with obs_tracing.span(
                    "trainer_fit", mesh=str(_json_safe(mesh or {}))
                ):
                    if session_name is not None:
                        with self.monitoring.trace(session_name):
                            trainer.fit(**params)
                    else:
                        trainer.fit(**params)
                fit_time = time.perf_counter() - t0
            # Epoch fence at publication: a stale-epoch straggler must
            # not overwrite the artifact a recovered orchestrator owns.
            self.ctx.require_current_epoch()
            self.ctx.volumes.save_object(artifact_type, name, instance)
            # A re-train just replaced this artifact's binary: a
            # serving registry holding the old params resident must
            # reload before the next request (same contract as the
            # single-device executor path).
            self.ctx.notify_artifact_changed(name)
            # Replace (not append) history rows on re-runs.
            for doc in self.ctx.documents.find(
                name, query={"docType": "history"}
            ):
                self.ctx.documents.delete_one(name, doc["_id"])
            store_history_rows(
                self.ctx.documents, name, dict(trainer.history)
            )
            cache_delta = compile_cache.delta_since(cache_before)
            if session_logdir is not None:
                # Cache counters ride into the tfevents file as
                # single-step scalars next to the training curves, so
                # TensorBoard shows whether this job traced (miss) or
                # warm-started (hit).
                logged = dict(trainer.history)
                logged.update({
                    f"compile_cache_{key}": [float(val)]
                    for key, val in cache_delta.items()
                })
                write_scalar_logs(session_logdir, logged, prefix=name)
            return {
                "fitTime": fit_time,
                "meshDevices": trainer.mesh.size,
                "compileCache": cache_delta,
            }

        self.ctx.engine.submit(
            name,
            run,
            description=description or f"distributed fit on {parent_name}",
            method="fit",
            parameters=_json_safe(training_parameters),
            on_success=lambda extra: extra,
            job_class="distributed",
        )

    # trainingParameters the cluster path can ship to agents: arrays go
    # via staged .npy files, scalars via JSON; anything else must be
    # rejected loudly, not silently dropped.
    _CLUSTER_ARRAY_KEYS = ("x", "y")

    def _submit_train_cluster(
        self, name, parent_name, parent_type, training_parameters,
        compile_spec, mesh, artifact_type, description, *,
        resume_default, session_logdir=None,
    ):
        """Cluster mode: fan the fit out to HostAgents through the task
        Coordinator — the reference's ``RayExecutor.run(train)`` shape
        (binary_execution.py:237-292), except the agents form ONE SPMD
        program over a global mesh instead of a Horovod ring, and the
        trained state comes home through the shared artifact volume,
        not as weight lists over the control plane.

        Monitoring caveat: profiler traces run on the agents, not here;
        the managed TensorBoard session still gets the scalar curves
        (written from the returned history after the job completes).
        """
        import shutil as _shutil

        import numpy as np

        from learningorchestra_tpu.parallel.coordinator import (
            submit_job,
            wait_job,
        )

        cfg = self.ctx.config.dist
        coord = cfg.task_coordinator
        world = int(cfg.num_processes)
        if world < 2:
            raise ValidationError(
                "cluster mode needs dist.num_processes >= 2 "
                "(LO_TPU_WORLD_SIZE) — one process per agent host"
            )
        # jax_coordinator is optional: when unset, the rank-0 agent
        # binds a port and publishes its address through the task
        # coordinator (launch._negotiate_rendezvous).
        jax_coord = cfg.jax_coordinator

        def run():
            params = dsl.resolve_params(
                training_parameters, self.ctx.loader
            )
            try:
                x = np.asarray(params.pop("x"))
                y = np.asarray(params.pop("y"))
            except KeyError as exc:
                raise ValidationError(
                    f"trainingParameters missing {exc} for cluster fit"
                ) from exc
            validation = params.pop("validation_data", None)
            fit_kwargs = {}
            unsupported = []
            for key, val in params.items():
                if val is None or isinstance(val, (int, float, bool, str)):
                    fit_kwargs[key] = val
                else:
                    unsupported.append(key)
            if unsupported:
                raise ValidationError(
                    f"cluster fit cannot ship parameters {unsupported} "
                    f"(arrays go via x/y/validation_data; callbacks are "
                    f"local-mode only)"
                )
            # Stage data on the shared volume; every agent host mounts
            # it (deploy/: the lo-data volume / RWX claim).
            stage = self.ctx.volumes.root / "_staging" / name
            stage.mkdir(parents=True, exist_ok=True)
            try:
                np.save(stage / "x.npy", x)
                np.save(stage / "y.npy", y)
                data = {
                    "x": str(stage / "x.npy"),
                    "y": str(stage / "y.npy"),
                }
                if validation is not None:
                    vx, vy = validation
                    np.save(stage / "vx.npy", np.asarray(vx))
                    np.save(stage / "vy.npy", np.asarray(vy))
                    data["vx"] = str(stage / "vx.npy")
                    data["vy"] = str(stage / "vy.npy")

                # Fresh runs must not resurrect a previous run's
                # checkpoints (same guard as the local path); an
                # in-engine preemption retry resumes its own run's
                # checkpoints instead of re-fitting from epoch 0.
                from learningorchestra_tpu.jobs import (
                    engine as engine_mod,
                )

                attempt = engine_mod.current_attempt()
                ckdir = self.ctx.checkpoint_dir(name)
                fit_kwargs.setdefault("resume", resume_default)
                if attempt > 0:
                    fit_kwargs["resume"] = True
                if not fit_kwargs["resume"] and ckdir.exists():
                    _shutil.rmtree(ckdir, ignore_errors=True)
                fit_kwargs["checkpoint_dir"] = str(ckdir)

                job_id = submit_job(
                    coord,
                    "lo.multihost_fit",
                    {
                        "jax_coordinator": jax_coord,
                        "estimator_volume": {
                            "volume_root": str(self.ctx.volumes.root),
                            "artifact_type": parent_type,
                            "name": parent_name,
                        },
                        "compile_spec": compile_spec,
                        "mesh": _json_safe(mesh or {}),
                        "data": data,
                        "fit": fit_kwargs,
                        "out": {
                            "volume_root": str(self.ctx.volumes.root),
                            "artifact_type": artifact_type,
                            "name": name,
                        },
                    },
                    n_agents=world,
                )
                from learningorchestra_tpu.obs import (
                    tracing as obs_tracing,
                )

                t0 = time.perf_counter()
                with obs_tracing.span(
                    "cluster_fit", world=world, clusterJob=job_id
                ):
                    job = wait_job(
                        coord, job_id, timeout=cfg.job_timeout_s,
                        poll_interval=1.0,
                    )
                if job["state"] != "finished":
                    raise RuntimeError(
                        f"cluster fit {job['state']}: {job.get('errors')}"
                    )
                fit_time = time.perf_counter() - t0
            finally:
                _shutil.rmtree(stage, ignore_errors=True)
            # Epoch fence: a pre-crash straggler whose cluster job
            # outlived the orchestrator must not rewrite the history
            # rows a recovered run owns.  (The agents' binary write
            # happens on their hosts and is out of this fence's
            # reach — the engine's fenced terminal commit still stops
            # the stale metadata from publishing.)
            self.ctx.require_current_epoch()
            rank0 = job["results"].get("0") or job["results"].get(0)
            history = (rank0 or {}).get("history") or {}
            for doc in self.ctx.documents.find(
                name, query={"docType": "history"}
            ):
                self.ctx.documents.delete_one(name, doc["_id"])
            store_history_rows(self.ctx.documents, name, history)
            if session_logdir is not None:
                write_scalar_logs(session_logdir, history, prefix=name)
            return {
                "fitTime": fit_time,
                "worldSize": world,
                "clusterJob": job_id,
            }

        self.ctx.engine.submit(
            name,
            run,
            description=description
            or f"cluster distributed fit on {parent_name}",
            method="fit",
            parameters=_json_safe(training_parameters),
            on_success=lambda extra: extra,
            job_class="distributed",
        )

    # -- distributed builder --------------------------------------------------

    def create_builder(
        self,
        name: str,
        *,
        function: str,
        function_parameters: dict | None = None,
        n_workers: int | None = None,
        artifact_type: str = DISTRIBUTED_BUILDER_TYPE,
        description: str = "",
    ) -> dict:
        self.ctx.require_new_name(name)
        if not function or not isinstance(function, str):
            raise ValidationError("missing 'function' code")
        fn_name = _validate_single_function(function)
        if n_workers is None:
            world = int(self.ctx.config.dist.num_processes or 1)
        else:
            try:
                world = int(n_workers)
            except (TypeError, ValueError):
                raise ValidationError("n_workers must be an integer")
        if not 1 <= world <= MAX_BUILDER_WORKERS:
            raise ValidationError(
                f"n_workers must be in [1, {MAX_BUILDER_WORKERS}]"
            )
        meta = self.ctx.artifacts.metadata.create(
            name,
            artifact_type,
            method=fn_name,
            extra={"worldSize": world},
        )

        def run():
            params = dsl.resolve_params(
                function_parameters, self.ctx.loader
            )
            globs: dict = {"__name__": f"builder_{name}"}
            exec(compile(function, f"<builder {name}>", "exec"),  # noqa: S102
                 globs)
            fn = globs[fn_name]

            def one_rank(rank: int):
                return fn(rank=rank, world_size=world, **params)

            with concurrent.futures.ThreadPoolExecutor(world) as pool:
                results = list(pool.map(one_rank, range(world)))
            self.ctx.volumes.save_object(artifact_type, name, results)
            for rank, result in enumerate(results):
                self.ctx.documents.insert_one(
                    name, {"rank": rank, "result": _json_safe(result)}
                )
            return {"worldSize": world}

        self.ctx.engine.submit(
            name,
            run,
            description=description or f"distributed builder ({world} ranks)",
            method=fn_name,
            parameters=_json_safe(function_parameters),
            on_success=lambda extra: extra,
            job_class="distributed",
        )
        return meta
