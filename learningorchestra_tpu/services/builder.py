"""Builder service: whole-pipeline execution.

Reference parity (microservices/builder_image/builder.py): one POST runs
modeling code to produce train/test feature frames, then fits up to five
classifiers **concurrently**, evaluates each (F1, accuracy, fitTime), and
stores per-row predictions — one artifact per classifier, named
``{test_dataset}{classifier}`` (builder_image/utils.py:41-44).

Differences by design: the classifiers are the JAX-native estimators (no
Spark cluster), and the "modeling code" contract accepts either the
reference's exec-style code string (sets ``features_training`` /
``features_testing`` / optional ``features_evaluation`` globals) or a
declarative field split.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from learningorchestra_tpu.services.context import (
    ServiceContext,
    ValidationError,
)
from learningorchestra_tpu.toolkit import registry

BUILDER_TYPE = "builder/sparkml"

# Classifier whitelist (reference: builder_image/utils.py:119-123) —
# MLlib-era names alias to the JAX estimators.
CLASSIFIERS = {
    "LogisticRegression": ("sklearn.linear_model", "LogisticRegression"),
    "DecisionTree": ("sklearn.tree", "DecisionTreeClassifier"),
    "RandomForest": ("sklearn.ensemble", "RandomForestClassifier"),
    "GradientBoosting": (
        "sklearn.ensemble", "GradientBoostingClassifier",
    ),
    "NaiveBayes": ("sklearn.naive_bayes", "GaussianNB"),
}


def _f1_macro(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    """Macro-averaged F1 (the reference records MLlib's F1,
    builder.py:117-142)."""
    classes = np.unique(np.concatenate([y_true, y_pred]))
    f1s = []
    for c in classes:
        tp = float(((y_pred == c) & (y_true == c)).sum())
        fp = float(((y_pred == c) & (y_true != c)).sum())
        fn = float(((y_pred != c) & (y_true == c)).sum())
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f1s.append(2 * prec * rec / (prec + rec) if prec + rec else 0.0)
    return float(np.mean(f1s))


class BuilderService:
    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    def create(
        self,
        *,
        training_dataset: str,
        test_dataset: str,
        classifiers: list[str],
        label_field: str = "label",
        feature_fields: list[str] | None = None,
        modeling_code: str | None = None,
        classifier_parameters: dict | None = None,
        description: str = "",
    ) -> list[dict]:
        self.ctx.require_finished_parent(training_dataset)
        self.ctx.require_finished_parent(test_dataset)
        if not classifiers:
            raise ValidationError(
                f"classifiersList must name at least one of "
                f"{sorted(CLASSIFIERS)}"
            )
        unknown = [c for c in classifiers if c not in CLASSIFIERS]
        if unknown:
            raise ValidationError(
                f"unknown classifiers: {unknown}; "
                f"allowed: {sorted(CLASSIFIERS)}"
            )
        metas = []
        for clf in classifiers:
            # Result name = test dataset + classifier (utils.py:41-44);
            # the reference pre-deletes a stale result, so re-POST works.
            result_name = f"{test_dataset}{clf}"
            if self.ctx.artifacts.metadata.exists(result_name):
                self.ctx.artifacts.delete(result_name)
                self.ctx.volumes.delete_everywhere(result_name)
            metas.append(
                self.ctx.artifacts.metadata.create(
                    result_name, BUILDER_TYPE,
                    parent_name=test_dataset,
                    extra={"classifier": clf},
                )
            )

        def prepare():
            train_df = self.ctx.loader.load_dataframe(training_dataset)
            test_df = self.ctx.loader.load_dataframe(test_dataset)
            if modeling_code:
                globs: dict = {
                    "training_df": train_df,
                    "testing_df": test_df,
                    "np": np,
                }
                exec(modeling_code, globs)  # noqa: S102 — builder parity
                feats_train = np.asarray(globs["features_training"])
                feats_test = np.asarray(globs["features_testing"])
                # Labels may come from the modeling code or (the
                # reference-parity shape, which only sets features_*) from
                # the datasets' label column.
                y_train = np.asarray(
                    globs["labels_training"]
                    if "labels_training" in globs
                    else train_df[label_field]
                ).reshape(-1)
                y_test = np.asarray(
                    globs["labels_testing"]
                    if "labels_testing" in globs
                    else test_df[label_field]
                ).reshape(-1)
            else:
                cols = feature_fields or [
                    c for c in train_df.columns if c != label_field
                ]
                feats_train = train_df[cols].to_numpy(dtype=np.float32)
                y_train = train_df[label_field].to_numpy()
                feats_test = test_df[cols].to_numpy(dtype=np.float32)
                y_test = test_df[label_field].to_numpy()
            return feats_train, y_train, feats_test, y_test

        def run_all():
            try:
                feats_train, y_train, feats_test, y_test = prepare()
            except BaseException as exc:
                # A pre-loop failure (dataset load, modeling code) must
                # surface on every visible result artifact — clients poll
                # those, not the hidden coordinator.
                for clf in classifiers:
                    result_name = f"{test_dataset}{clf}"
                    self.ctx.artifacts.metadata.mark_failed(
                        result_name, repr(exc)
                    )
                    self.ctx.artifacts.ledger.record(
                        result_name, state="failed", exception=repr(exc)
                    )
                raise

            def run_one(clf: str):
                result_name = f"{test_dataset}{clf}"
                try:
                    self.ctx.artifacts.metadata.mark_running(result_name)
                    mod, cls = CLASSIFIERS[clf]
                    kwargs = (classifier_parameters or {}).get(clf, {})
                    model = registry.resolve(mod, cls)(**kwargs)
                    t0 = time.perf_counter()
                    model.fit(feats_train, y_train)
                    fit_time = time.perf_counter() - t0
                    preds = np.asarray(model.predict(feats_test)).reshape(-1)
                    acc = float((preds == y_test).mean())
                    f1 = _f1_macro(y_test, preds)
                    self.ctx.documents.insert_many(
                        result_name,
                        (
                            {"prediction": p, "label": t}
                            for p, t in zip(
                                _tolist(preds), _tolist(y_test)
                            )
                        ),
                    )
                    self.ctx.volumes.save_object(
                        BUILDER_TYPE, result_name, model
                    )
                    self.ctx.artifacts.metadata.mark_finished(
                        result_name,
                        {
                            "fitTime": fit_time,
                            "accuracy": acc,
                            "F1": f1,
                        },
                    )
                    self.ctx.artifacts.ledger.record(
                        result_name,
                        description=description,
                        state="finished",
                        metrics={
                            "fitTime": fit_time, "accuracy": acc, "F1": f1,
                        },
                    )
                except BaseException as exc:
                    self.ctx.artifacts.metadata.mark_failed(
                        result_name, repr(exc)
                    )
                    self.ctx.artifacts.ledger.record(
                        result_name, state="failed", exception=repr(exc)
                    )

            # Concurrent classifier training (reference trains its five
            # MLlib classifiers in threads, builder.py:62-78).
            with ThreadPoolExecutor(max_workers=len(classifiers)) as pool:
                list(pool.map(run_one, classifiers))

        # One coordinating job; per-classifier status lives in each
        # result artifact's own metadata.
        coordinator = f"{test_dataset}__builder_run"
        if self.ctx.artifacts.metadata.exists(coordinator):
            self.ctx.artifacts.delete(coordinator)
        self.ctx.artifacts.metadata.create(
            coordinator, BUILDER_TYPE,
            extra={"classifiers": classifiers, "hidden": True},
        )
        self.ctx.engine.submit(
            coordinator, run_all, description=description or "builder run",
            job_class="builder",
        )
        return metas


def _tolist(arr: np.ndarray) -> list:
    return [
        v.item() if isinstance(v, np.generic) else v for v in arr.tolist()
    ] if hasattr(arr, "tolist") else list(arr)
