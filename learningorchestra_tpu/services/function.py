"""Function service — the arbitrary-code escape hatch.

Reference parity (microservices/code_executor_image/): POST a Python
function body (inline string or fetched from a URL) plus DSL-treated
parameters; the code runs with the parameters as globals and must set a
``response`` variable; stdout is captured into the execution document
(code_execution.py:149-196, utils.py:113-138).

This is the ONE place arbitrary code remains by design (SURVEY §7 "hard
parts": the exec boundary).  Everything else in the framework is
declarative registry specs; ``function/python`` keeps the reference's
full power for host-side glue code.  The code runs in the service
process — the trust model is the reference's (the API is the audience's
own cluster, not a public service).
"""

from __future__ import annotations

from learningorchestra_tpu import dsl
from learningorchestra_tpu.log import capture_thread_stdout
from learningorchestra_tpu.services.context import (
    ServiceContext,
    ValidationError,
)

FUNCTION_TYPE = "function/python"


def _fetch_code(function: str) -> str:
    """Inline code or, if it looks like a URL, fetch it (reference:
    code_execution.py:11-21)."""
    if function.startswith(("http://", "https://")):
        import requests

        resp = requests.get(function, timeout=60)
        resp.raise_for_status()
        return resp.text
    return function


class FunctionService:
    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    def create(
        self,
        name: str,
        *,
        function: str,
        function_parameters: dict | None = None,
        description: str = "",
        deadline_s: float | None = None,
    ) -> dict:
        self.ctx.require_new_name(name)
        if not function or not isinstance(function, str):
            raise ValidationError("missing 'function' code")
        meta = self.ctx.artifacts.metadata.create(
            name, FUNCTION_TYPE, extra={"description": description}
        )
        self._submit(name, function, function_parameters, description,
                     deadline_s=deadline_s)
        return meta

    def update(
        self,
        name: str,
        *,
        function: str,
        function_parameters: dict | None = None,
        description: str = "",
        deadline_s: float | None = None,
    ) -> dict:
        self.ctx.require_existing(name)
        if not function or not isinstance(function, str):
            raise ValidationError("missing 'function' code")
        self.ctx.artifacts.metadata.restart(name)
        self._submit(name, function, function_parameters, description,
                     deadline_s=deadline_s)
        return self.ctx.artifacts.metadata.read(name)

    def _submit(self, name, function, function_parameters, description,
                *, deadline_s=None):
        def run():
            code = _fetch_code(function)
            params = dsl.resolve_params(
                function_parameters, self.ctx.loader
            )
            globs: dict = {"__name__": f"function_{name}"}
            globs.update(params)
            # Thread-scoped capture: redirect_stdout would swap stdout
            # for the WHOLE process, stealing concurrent jobs' (and the
            # server's own) prints into this job's document.
            with capture_thread_stdout() as buf:
                exec(code, globs)  # noqa: S102 — the documented escape hatch
            if "response" not in globs:
                raise ValidationError(
                    "function code must set a 'response' variable"
                )
            response = globs["response"]
            self.ctx.volumes.save_object(FUNCTION_TYPE, name, response)
            from learningorchestra_tpu.services.executor import _json_safe

            self.ctx.documents.insert_one(
                name,
                {
                    "result": _json_safe(response),
                    "functionMessage": buf.getvalue(),
                },
            )
            return response

        # Arbitrary code is the MOST hang-prone surface the system
        # offers — the per-submit deadline matters here even more than
        # on train jobs (None inherits the engine default).
        self.ctx.engine.submit(
            name, run, description=description or "python function",
            capture_stdout=False,
            job_class="function",
            deadline_s=deadline_s,
        )

    def delete(self, name: str) -> None:
        self.ctx.delete_artifact(name)
