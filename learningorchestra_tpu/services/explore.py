"""Explore service: histograms and plot-producing executions.

Reference parity:
- **histogram** — per-field value counts via Mongo ``$group``/``$sum``
  into a new collection, one document per field
  (microservices/histogram_image/histogram.py:13-44);
- **generic explore** — run a registry class/method (e.g. PCA, TSNE) and
  render a scatterplot PNG served back via GET
  (database_executor_image/utils.py:295-320, server.py:151-166) — here
  rendered with matplotlib (no seaborn dependency on the hot path).
"""

from __future__ import annotations

from learningorchestra_tpu import dsl
from learningorchestra_tpu.services.context import (
    ServiceContext,
    ValidationError,
)
from learningorchestra_tpu.toolkit import registry

HISTOGRAM_TYPE = "explore/histogram"


class ExploreService:
    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    # -- histogram ------------------------------------------------------------

    def create_histogram(
        self, name: str, parent_name: str, fields: list[str]
    ) -> dict:
        parent = self.ctx.require_finished_parent(parent_name)
        self.ctx.require_new_name(name)
        known = parent.get("fields") or []
        missing = [f for f in fields if known and f not in known]
        if missing:
            raise ValidationError(f"fields not in parent: {missing}")
        meta = self.ctx.artifacts.metadata.create(
            name, HISTOGRAM_TYPE, parent_name=parent_name,
            extra={"fields": fields},
        )

        def run():
            for field in fields:
                counts = self.ctx.documents.aggregate_counts(
                    parent_name, field
                )
                self.ctx.documents.insert_one(
                    name,
                    {
                        "field": field,
                        "counts": {str(k): v for k, v in counts.items()},
                    },
                )
            return {"fields": fields}

        self.ctx.engine.submit(
            name, run, description=f"histogram of {parent_name}.{fields}",
            on_success=lambda r: r,
        )
        return meta

    # -- plot-producing execution --------------------------------------------

    def create_plot(
        self,
        name: str,
        *,
        module_path: str,
        class_name: str,
        class_parameters: dict | None = None,
        method: str = "fit_transform",
        method_parameters: dict | None = None,
        artifact_type: str = "explore/tensorflow",
        color_by: str | None = None,
        description: str = "",
    ) -> dict:
        """Run e.g. TSNE/PCA on a dataset and persist a scatter PNG."""
        self.ctx.require_new_name(name)
        factory = registry.resolve(module_path, class_name)
        if not registry.validate_method(factory, method):
            raise ValidationError(f"no such method: {method!r}")
        meta = self.ctx.artifacts.metadata.create(
            name,
            artifact_type,
            module_path=module_path,
            class_name=class_name,
            method=method,
            # Persisted so a PATCH re-run can re-render without the
            # original request body.
            extra={
                "classParameters": class_parameters or {},
                "colorBy": color_by,
            },
        )
        self._submit_plot(
            name, factory, class_parameters, method, method_parameters,
            artifact_type, color_by, description, class_name,
        )
        return meta

    def update_plot(
        self,
        name: str,
        *,
        class_parameters: dict | None = None,
        method_parameters: dict | None = None,
        color_by: str | None = None,
        description: str = "",
    ) -> dict:
        """PATCH re-run of a plot execution (reference: PATCH
        /explore/{t} → database_executor_image/server.py:91-148): flips
        ``finished`` False and re-renders, with new parameters when
        given, else the original request's."""
        meta = self.ctx.require_not_running(name)
        module_path = meta.get("modulePath")
        class_name = meta.get("class")
        if not module_path or not class_name:
            raise ValidationError(
                f"{name!r} is not a re-runnable explore execution"
            )
        factory = registry.resolve(module_path, class_name)
        if class_parameters is None:
            class_parameters = meta.get("classParameters") or {}
        if method_parameters is None:
            method_parameters = self.ctx.last_recorded_parameters(name)
        if color_by is None:
            color_by = meta.get("colorBy")
        self.ctx.artifacts.metadata.restart(name)
        self._submit_plot(
            name, factory, class_parameters, meta.get("method"),
            method_parameters, meta.get("type"), color_by, description,
            class_name,
        )
        return self.ctx.artifacts.metadata.read(name)

    def _submit_plot(
        self, name, factory, class_parameters, method, method_parameters,
        artifact_type, color_by, description, class_name,
    ) -> None:
        def run():
            import numpy as np

            cls_params = dsl.resolve_params(class_parameters, self.ctx.loader)
            m_params = dsl.resolve_params(method_parameters, self.ctx.loader)
            instance = factory(**cls_params)
            result = np.asarray(getattr(instance, method)(**m_params))
            colors = None
            if color_by is not None:
                colors = np.asarray(
                    dsl.resolve_value(color_by, self.ctx.loader)
                ).reshape(-1)
            png_path = self._render_scatter(name, artifact_type, result,
                                            colors)
            return {"image": str(png_path)}

        self.ctx.engine.submit(
            name, run, description=description or f"{class_name} plot",
            method=method, parameters=method_parameters,
            on_success=lambda r: r,
        )

    def _render_scatter(self, name, artifact_type, points, colors=None):
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(8, 6), dpi=120)
        if points.ndim != 2 or points.shape[1] < 2:
            raise ValidationError(
                "plot execution must produce (n, >=2) points"
            )
        sc = ax.scatter(
            points[:, 0], points[:, 1], c=colors, s=8, cmap="viridis",
            alpha=0.8,
        )
        if colors is not None:
            fig.colorbar(sc, ax=ax)
        ax.set_title(name)
        path = self.ctx.volumes.path_for(artifact_type, name)
        path.parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(path, format="png", bbox_inches="tight")
        plt.close(fig)
        return path

    def read_image(self, name: str) -> bytes:
        """GET the rendered PNG (reference streams it with send_file,
        database_executor_image/server.py:151-166)."""
        meta = self.ctx.require_existing(name)
        return self.ctx.volumes.read_bytes(meta.get("type", ""), name)
