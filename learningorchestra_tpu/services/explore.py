"""Explore service: histograms and plot-producing executions.

Reference parity:
- **histogram** — per-field value counts via Mongo ``$group``/``$sum``
  into a new collection, one document per field
  (microservices/histogram_image/histogram.py:13-44);
- **generic explore** — run a registry class/method (e.g. PCA, TSNE) and
  render a scatterplot PNG served back via GET
  (database_executor_image/utils.py:295-320, server.py:151-166) — here
  rendered with matplotlib (no seaborn dependency on the hot path).
"""

from __future__ import annotations

from learningorchestra_tpu import dsl
from learningorchestra_tpu.services.context import (
    ServiceContext,
    ValidationError,
)
from learningorchestra_tpu.toolkit import registry

HISTOGRAM_TYPE = "explore/histogram"
CURVES_TYPE = "explore/curves"


class ExploreService:
    def __init__(self, ctx: ServiceContext):
        self.ctx = ctx

    # -- histogram ------------------------------------------------------------

    def create_histogram(
        self, name: str, parent_name: str, fields: list[str]
    ) -> dict:
        parent = self.ctx.require_finished_parent(parent_name)
        self.ctx.require_new_name(name)
        known = parent.get("fields") or []
        missing = [f for f in fields if known and f not in known]
        if missing:
            raise ValidationError(f"fields not in parent: {missing}")
        meta = self.ctx.artifacts.metadata.create(
            name, HISTOGRAM_TYPE, parent_name=parent_name,
            extra={"fields": fields},
        )

        def run():
            for field in fields:
                counts = self.ctx.documents.aggregate_counts(
                    parent_name, field
                )
                self.ctx.documents.insert_one(
                    name,
                    {
                        "field": field,
                        "counts": {str(k): v for k, v in counts.items()},
                    },
                )
            return {"fields": fields}

        self.ctx.engine.submit(
            name, run, description=f"histogram of {parent_name}.{fields}",
            on_success=lambda r: r,
            job_class="explore",
        )
        return meta

    # -- training curves ------------------------------------------------------

    def create_curves(
        self, name: str, parent_name: str,
        fields: list[str] | None = None,
    ) -> dict:
        """Render a train artifact's per-epoch history (the durable
        ``docType=history`` rows every fit surface stores) as a curves
        PNG — loss-family on the left axis, score-family on the right.
        The reference offers no training visualization beyond raw
        TensorBoard; this serves the keras-history contract as an
        explore artifact behind the same GET-the-PNG route."""
        self.ctx.require_finished_parent(parent_name)
        self.ctx.require_new_name(name)
        meta = self.ctx.artifacts.metadata.create(
            name, CURVES_TYPE, parent_name=parent_name,
            extra={"fields": fields},
        )
        self._submit_curves(name, parent_name, fields)
        return meta

    def update_curves(self, name: str,
                      fields: list[str] | None = None) -> dict:
        """PATCH re-run: re-reads the parent's CURRENT history rows —
        the natural refresh after more training epochs land.  A new
        ``fields`` selection replaces the stored one (same PATCH
        semantics as ``update_plot``); omitted, the original sticks."""
        meta = self.ctx.require_not_running(name)
        if meta.get("type") != CURVES_TYPE:
            raise ValidationError(f"{name!r} is not a curves explore")
        self.ctx.require_finished_parent(meta.get("parentName"))
        if fields is None:
            fields = meta.get("fields")
        else:
            self.ctx.artifacts.metadata.update(name, {"fields": fields})
        self.ctx.artifacts.metadata.restart(name)
        self._submit_curves(name, meta["parentName"], fields)
        return self.ctx.artifacts.metadata.read(name)

    def _submit_curves(self, name, parent_name, fields) -> None:
        def run():
            rows = self.ctx.documents.find(
                parent_name, query={"docType": "history"}
            )
            if not rows:
                raise ValueError(
                    f"{parent_name!r} has no history rows — train it "
                    "first (or it is not a train artifact)"
                )
            rows.sort(key=lambda r: r.get("epoch", 0))
            series: dict[str, list] = {}
            for row in rows:
                for key, val in row.items():
                    if key in ("_id", "docType", "epoch"):
                        continue
                    if isinstance(val, (int, float)):
                        series.setdefault(key, []).append(float(val))
            if fields:
                missing = [f for f in fields if f not in series]
                if missing:
                    raise ValueError(
                        f"metrics not in history: {missing}; "
                        f"available: {sorted(series)}"
                    )
                series = {k: series[k] for k in fields}
            else:
                # Default view: drop throughput/timing bookkeeping.
                series = {
                    k: v for k, v in series.items()
                    if k not in ("epoch_time", "samples_per_sec")
                } or series
            png_path = self._render_curves(name, series)
            return {
                "image": str(png_path),
                "epochs": max(len(v) for v in series.values()),
                "metrics": sorted(series),
            }

        self.ctx.engine.submit(
            name, run,
            description=f"training curves of {parent_name}",
            on_success=lambda r: r,
            job_class="explore",
        )

    def _save_png(self, fig, name: str, artifact_type: str):
        """Shared PNG commit for every explore renderer: one place for
        the path layout and savefig knobs."""
        import matplotlib.pyplot as plt

        path = self.ctx.volumes.path_for(artifact_type, name)
        path.parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(path, format="png", bbox_inches="tight")
        plt.close(fig)
        return path

    def _render_curves(self, name, series: dict):
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(8, 6), dpi=120)
        loss_like = {
            k: v for k, v in series.items()
            if "loss" in k or "perplexity" in k
        }
        score_like = {k: v for k, v in series.items() if k not in loss_like}
        for key, vals in sorted(loss_like.items()):
            ax.plot(range(1, len(vals) + 1), vals, marker="o",
                    markersize=3, label=key)
        ax.set_xlabel("epoch")
        ax.set_ylabel("loss")
        handles, labels = ax.get_legend_handles_labels()
        if score_like:
            ax2 = ax.twinx()
            for key, vals in sorted(score_like.items()):
                ax2.plot(range(1, len(vals) + 1), vals, marker="s",
                         markersize=3, linestyle="--", label=key)
            ax2.set_ylabel("score")
            h2, l2 = ax2.get_legend_handles_labels()
            handles, labels = handles + h2, labels + l2
        if handles:
            ax.legend(handles, labels, loc="best", fontsize=8)
        ax.set_title(name)
        return self._save_png(fig, name, CURVES_TYPE)

    # -- plot-producing execution --------------------------------------------

    def create_plot(
        self,
        name: str,
        *,
        module_path: str,
        class_name: str,
        class_parameters: dict | None = None,
        method: str = "fit_transform",
        method_parameters: dict | None = None,
        artifact_type: str = "explore/tensorflow",
        color_by: str | None = None,
        description: str = "",
    ) -> dict:
        """Run e.g. TSNE/PCA on a dataset and persist a scatter PNG."""
        self.ctx.require_new_name(name)
        factory = registry.resolve(module_path, class_name)
        if not registry.validate_method(factory, method):
            raise ValidationError(f"no such method: {method!r}")
        meta = self.ctx.artifacts.metadata.create(
            name,
            artifact_type,
            module_path=module_path,
            class_name=class_name,
            method=method,
            # Persisted so a PATCH re-run can re-render without the
            # original request body.
            extra={
                "classParameters": class_parameters or {},
                "colorBy": color_by,
            },
        )
        self._submit_plot(
            name, factory, class_parameters, method, method_parameters,
            artifact_type, color_by, description, class_name,
        )
        return meta

    def update_plot(
        self,
        name: str,
        *,
        class_parameters: dict | None = None,
        method_parameters: dict | None = None,
        color_by: str | None = None,
        description: str = "",
    ) -> dict:
        """PATCH re-run of a plot execution (reference: PATCH
        /explore/{t} → database_executor_image/server.py:91-148): flips
        ``finished`` False and re-renders, with new parameters when
        given, else the original request's."""
        meta = self.ctx.require_not_running(name)
        module_path = meta.get("modulePath")
        class_name = meta.get("class")
        if not module_path or not class_name:
            raise ValidationError(
                f"{name!r} is not a re-runnable explore execution"
            )
        factory = registry.resolve(module_path, class_name)
        if class_parameters is None:
            class_parameters = meta.get("classParameters") or {}
        if method_parameters is None:
            method_parameters = self.ctx.last_recorded_parameters(name)
        if color_by is None:
            color_by = meta.get("colorBy")
        self.ctx.artifacts.metadata.restart(name)
        self._submit_plot(
            name, factory, class_parameters, meta.get("method"),
            method_parameters, meta.get("type"), color_by, description,
            class_name,
        )
        return self.ctx.artifacts.metadata.read(name)

    def _submit_plot(
        self, name, factory, class_parameters, method, method_parameters,
        artifact_type, color_by, description, class_name,
    ) -> None:
        def run():
            import numpy as np

            cls_params = dsl.resolve_params(class_parameters, self.ctx.loader)
            m_params = dsl.resolve_params(method_parameters, self.ctx.loader)
            instance = factory(**cls_params)
            result = np.asarray(getattr(instance, method)(**m_params))
            colors = None
            if color_by is not None:
                colors = np.asarray(
                    dsl.resolve_value(color_by, self.ctx.loader)
                ).reshape(-1)
            png_path = self._render_scatter(name, artifact_type, result,
                                            colors)
            return {"image": str(png_path)}

        self.ctx.engine.submit(
            name, run, description=description or f"{class_name} plot",
            method=method, parameters=method_parameters,
            on_success=lambda r: r,
            job_class="explore",
        )

    def _render_scatter(self, name, artifact_type, points, colors=None):
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(8, 6), dpi=120)
        if points.ndim != 2 or points.shape[1] < 2:
            raise ValidationError(
                "plot execution must produce (n, >=2) points"
            )
        sc = ax.scatter(
            points[:, 0], points[:, 1], c=colors, s=8, cmap="viridis",
            alpha=0.8,
        )
        if colors is not None:
            fig.colorbar(sc, ax=ax)
        ax.set_title(name)
        return self._save_png(fig, name, artifact_type)

    def read_image(self, name: str) -> bytes:
        """GET the rendered PNG (reference streams it with send_file,
        database_executor_image/server.py:151-166)."""
        meta = self.ctx.require_existing(name)
        return self.ctx.volumes.read_bytes(meta.get("type", ""), name)
