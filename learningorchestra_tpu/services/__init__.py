"""Service layer: the business logic behind every REST route group.

The reference runs nine Flask microservices with near-identical internal
shape (SURVEY §1 L2).  Here each service is a plain class over a shared
:class:`ServiceContext`; the API layer maps the reference's route table
onto them.  The microservice-per-container split was a deployment choice,
not a capability — one process serves all route groups, and the job engine
provides the same async semantics the per-service thread pools did.
"""

from learningorchestra_tpu.services.context import ServiceContext
from learningorchestra_tpu.services.dataset import DatasetService
from learningorchestra_tpu.services.transform import TransformService
from learningorchestra_tpu.services.explore import ExploreService
from learningorchestra_tpu.services.model import ModelService
from learningorchestra_tpu.services.executor import ExecutorService
from learningorchestra_tpu.services.function import FunctionService
from learningorchestra_tpu.services.builder import BuilderService

__all__ = [
    "ServiceContext",
    "DatasetService",
    "TransformService",
    "ExploreService",
    "ModelService",
    "ExecutorService",
    "FunctionService",
    "BuilderService",
]
