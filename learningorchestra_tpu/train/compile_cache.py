"""Process-wide compiled-program cache — trace once, run many.

Every submitted train/tune job used to rebuild its jitted epoch/eval
closures from scratch (``train/neural.py`` ``build_*_epoch_fns``), so an
identical second job — or every candidate of a tune sweep sharing one
architecture — re-paid full Python tracing and XLA compilation even
though jax's per-function jit cache would have served it instantly *had
the function object survived*.  The persistent XLA cache
(services/context.py) only dedups the XLA compile step; Python tracing
and closure construction were still repeated per job, and on TPU a
trace alone is seconds for the zoo's larger models.

This module keeps the jitted callables themselves alive across jobs,
keyed by a canonical fingerprint of the *program*:

  (builder kind, model architecture spec, optimizer config, loss kind,
   compute dtype, batch/dataset shape, donation flags, mesh layout)

On a hit the caller gets the exact wrapper a previous job compiled —
jax's C++ fastpath then dispatches with zero tracing.  On a miss the
builder runs once; concurrent callers for the same key (tune candidates
submit together) coalesce onto the single build instead of racing N
identical traces.

Correctness notes:

- optax transforms and flax modules are pure: a cached callable closing
  over job A's optimizer/module objects is behaviorally identical for
  job B *iff the fingerprints match*, which is exactly what the key
  guarantees.  Opaque optimizer objects (no declarative spec) cannot be
  fingerprinted and fall back to identity keys — correct, merely
  uncached across jobs.
- mesh-aware modules (models/longcontext.py) carry their bound ``Mesh``
  as a dataclass field, so the module fingerprint distinguishes
  ring-attention-for-mesh-X from vanilla automatically; distributed
  entries additionally key on mesh axis names + device assignment.
- the cache clears itself whenever the visible device set changes
  (TPU restart, tunnel reattach): compiled executables pin device
  handles that are dead afterwards.

Observability: hit/miss/eviction/trace-time counters (``stats()``)
surface through the monitoring service endpoint
(GET /monitoring/<tool>/compileCache), per-job metadata deltas
(services/executor.py) and the tfevents writer on monitored distributed
jobs.  Sizing knobs live in config.py (LO_TPU_COMPILE_CACHE_*).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Callable

from learningorchestra_tpu.concurrency_rt import make_lock

__all__ = [
    "CompiledProgramCache",
    "apply_program_key",
    "canonical",
    "fingerprint",
    "get_cache",
    "module_fingerprint",
    "optimizer_fingerprint",
    "program_key",
    "reset_cache",
    "counters_snapshot",
    "delta_since",
    "warm_fingerprint",
]


def _faults():
    """Lazy fault-plane handle: the cache is imported from low-level
    train paths; keep its import graph flat."""
    from learningorchestra_tpu import faults

    return faults


def _costs():
    """Lazy cost-ledger handle (obs/costs.py), same discipline: every
    build notes a ProgramCost entry, and inserts charge the MEASURED
    serialized size against the byte cap when an analysis produced
    one."""
    from learningorchestra_tpu.obs import costs

    return costs


def _aot():
    """Lazy durable-executable-store handle (train/aot_store.py): a
    miss consults the on-disk AOT store before paying a live trace."""
    from learningorchestra_tpu.train import aot_store

    return aot_store


def _flight():
    """Lazy flight-recorder handle (obs/flight.py): builds and AOT
    restores land in the ``compile`` ring of the incident timeline."""
    from learningorchestra_tpu.obs import flight

    return flight


# -- canonical fingerprinting -------------------------------------------------


def canonical(obj: Any) -> Any:
    """Reduce ``obj`` to a deterministic, repr-stable structure.

    Handles the vocabulary a training-program spec is made of: flax
    modules (class identity + dataclass fields, recursively), meshes
    (axis names + shape + device assignment), dicts/sequences, dtypes
    and numpy scalars.  Anything unrecognized degrades to an
    identity-keyed token — correct (never a false hit), merely
    uncacheable across distinct objects.
    """
    # Late imports keep this module importable without initializing jax.
    import numpy as np

    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, (np.integer, np.floating, np.bool_)):
        return obj.item()
    if isinstance(obj, dict):
        return (
            "dict",
            tuple(sorted((str(k), canonical(v)) for k, v in obj.items())),
        )
    if isinstance(obj, (list, tuple)):
        return ("seq", tuple(canonical(v) for v in obj))
    if isinstance(obj, (set, frozenset)):
        return ("set", tuple(sorted(repr(canonical(v)) for v in obj)))
    # numpy/jax dtypes stringify deterministically.
    if isinstance(obj, np.dtype) or (
        isinstance(obj, type) and issubclass(obj, np.generic)
    ):
        return ("dtype", np.dtype(obj).name)
    try:
        from flax import linen as nn

        if isinstance(obj, nn.Module):
            return module_fingerprint(obj)
    except Exception:  # pragma: no cover — flax always present here
        pass
    try:
        from jax.sharding import Mesh

        if isinstance(obj, Mesh):
            return mesh_fingerprint(obj)
    except Exception:  # pragma: no cover
        pass
    if callable(obj):
        # Named functions (e.g. an activation passed as a module field)
        # key on their qualified name; lambdas/closures can't be proven
        # equal, so they key on identity (never a false hit).
        name = getattr(obj, "__qualname__", "")
        mod = getattr(obj, "__module__", "")
        if name and "<lambda>" not in name and "<locals>" not in name:
            return ("fn", mod, name)
        return ("opaque", id(obj))
    return ("opaque", id(obj))


def module_fingerprint(module: Any) -> Any:
    """Canonical spec of a flax module: class identity plus every
    dataclass field (``parent``/``name`` are flax bookkeeping, not
    architecture), recursing into nested modules and meshes."""
    fields = tuple(
        (f.name, canonical(getattr(module, f.name, None)))
        for f in dataclasses.fields(module)
        if f.name not in ("parent", "name")
    )
    return (
        "module",
        type(module).__module__,
        type(module).__qualname__,
        fields,
    )


def mesh_fingerprint(mesh: Any) -> Any:
    """Axis names + per-axis sizes + flat device assignment — two jobs
    share a sharded program only on the SAME devices in the SAME order
    (executables pin device handles)."""
    return (
        "mesh",
        tuple(str(a) for a in mesh.axis_names),
        tuple(sorted((str(k), int(v)) for k, v in mesh.shape.items())),
        tuple(
            (int(d.id), str(getattr(d, "platform", "")))
            for d in mesh.devices.flat
        ),
    )


def optimizer_fingerprint(estimator: Any) -> Any:
    """Optimizer identity as the REST surface expresses it: the
    declarative spec (name/dict/None) + learning rate (float or
    schedule spec) + accumulation wrapping.  An opaque optax object
    passed programmatically has no spec — key on identity, which keeps
    per-instance reuse but (correctly) never matches across jobs."""
    spec = getattr(estimator, "_optimizer_spec", None)
    if spec is None and estimator.optimizer is not None:
        # id() reuse after GC cannot produce a false hit: the cached
        # callable closes over this very optimizer object, so while an
        # entry keyed on this id lives, the object lives and the id
        # stays taken; once evicted there is no entry left to hit.
        return ("opaque", id(estimator.optimizer))
    return (
        "opt",
        canonical(spec),
        canonical(getattr(estimator, "learning_rate", None)),
        int(getattr(estimator, "_accumulate_steps", 1)),
    )


def fingerprint(*parts: Any) -> str:
    """Stable digest of canonicalized parts — the cache key."""
    payload = repr(tuple(canonical(p) for p in parts))
    return hashlib.sha256(payload.encode()).hexdigest()


def program_key(
    kind: str,
    *,
    module: Any,
    optimizer: Any,
    loss: Any,
    dtype: Any,
    shapes: Any = None,
    mesh: Any = None,
    donate: Any = None,
) -> str:
    """Fingerprint one compiled training program.

    ``optimizer`` should already be a canonical token (see
    :func:`optimizer_fingerprint`); ``shapes`` carries whatever the
    builder bakes into the trace (dataset length, batch size, shuffle,
    epoch count); ``mesh`` the trainer-level mesh fingerprint for
    sharded variants.
    """
    return fingerprint(
        kind, module, optimizer, str(loss), str(dtype), shapes, mesh,
        donate,
    )


def apply_program_key(module: Any, *, rows: int | None = None) -> str:
    """Key for a pure-inference ``apply`` program.

    Optimizer and loss play no part in inference, so every consumer of
    an architecture shares one program family.  ``rows`` is the
    SHAPE-BUCKET dimension (a serving bucket or predict's batch size):
    keyed this way, a whole deployment compiles at most one executable
    per (architecture, bucket) and the cache's miss counter counts
    buckets — never requests.  The one place the predict/serve key
    scheme lives; train/neural.py and serve/ both resolve through it.
    """
    return program_key(
        "apply",
        module=module_fingerprint(module),
        optimizer=None,
        loss="-",
        dtype="-",
        shapes=None if rows is None else ("rows", int(rows)),
    )


def _device_signature() -> tuple:
    """Identity of the visible device set; compiled executables are
    invalid the moment this changes (restarted TPU runtime, reattached
    tunnel, resized slice)."""
    import jax

    try:
        return tuple(
            (int(d.id), str(getattr(d, "platform", "")))
            for d in jax.devices()
        )
    except Exception:  # backend not initialized yet / unavailable
        return ()


def _record_compile_span(built_s: float, label, key: str) -> None:
    """Trace span for one program build: the build IS the "where did
    the time go" event this cache exists to amortize — a traced job
    shows each miss as a compile span nested where it happened (inside
    the lease, under the job root), including when the cache is
    disabled and every lookup builds.  No-op outside an active trace;
    never fails a build."""
    try:
        from learningorchestra_tpu.obs import tracing

        tracing.record_span(
            "compile", built_s, label=label or "", key=key[:12]
        )
    except Exception:  # noqa: BLE001
        pass


# -- durable warm start -------------------------------------------------------

#: Request knobs that do not shape the traced program: two submissions
#: differing only here share every compiled executable, so the warm
#: hint must treat them as identical.
_WARM_HINT_EXCLUDE = frozenset((
    "verbose", "description", "monitoring_path", "monitoringPath",
    "checkpoint_dir", "checkpointDir", "resume",
))


def warm_fingerprint(module_path, class_name, method,
                     parameters: dict | None = None) -> str:
    """Program-level warm-start hint for the engine's dispatcher.

    The old hint was ``module:class:method`` — coarse enough that two
    tune candidates with different optimizers (different programs!)
    claimed the same warmth.  This fingerprints the SUBMITTED SPEC
    through the same canonicalizer the cache keys use, minus the knobs
    that never reach a trace (verbosity, monitoring/checkpoint paths),
    so warm-start preference actually predicts cache hits.  Still a
    HINT: exact matching happens inside the cache; a collision merely
    reorders one class's queue."""
    params = {
        k: v for k, v in (parameters or {}).items()
        if k not in _WARM_HINT_EXCLUDE
    }
    return fingerprint(
        "warm", str(module_path), str(class_name), str(method), params
    )


class _AOTRestored:
    """A deserialized AOT executable standing in for the jit wrapper a
    builder would have produced, with a one-shot live-rebuild fallback.

    A restored ``Compiled`` pins the exact input avatars of the
    original trace, so an argument shape/dtype it never saw raises
    where a jit wrapper would simply re-trace.  The first call failure
    rebuilds live through the builder captured at lookup time and
    permanently swaps the rebuilt program in (counted store-side as a
    ``callFallbacks``); the request re-raises only if the REBUILT
    program fails too — genuine errors stay errors, stale executables
    cost one re-trace."""

    __slots__ = ("_fn", "_builder", "_key", "_label", "_fell_back")

    def __init__(self, fn, builder, key, label):
        self._fn = fn
        self._builder = builder
        self._key = key
        self._label = label
        self._fell_back = False

    def bind_builder(self, builder) -> None:
        """Boot pre-warm restores with no builder in hand; the first
        ``get_or_build`` hit re-arms the fallback with its caller's."""
        if self._builder is None:
            self._builder = builder

    def __call__(self, *args, **kwargs):
        if self._fell_back:
            return self._fn(*args, **kwargs)
        try:
            return self._fn(*args, **kwargs)
        except Exception:
            builder = self._builder
            if builder is None:
                raise
            self._fell_back = True
            t0 = time.perf_counter()
            rebuilt = builder()
            if isinstance(rebuilt, tuple):
                rebuilt = rebuilt[0]
            self._fn = rebuilt
            _record_compile_span(
                time.perf_counter() - t0, self._label, self._key
            )
            try:
                store = _aot().get_store()
                if store is not None:
                    store.note_call_fallback()
            except Exception:  # noqa: BLE001 — accounting only
                pass
            return self._fn(*args, **kwargs)


# -- the cache ---------------------------------------------------------------


class _Entry:
    __slots__ = ("value", "nbytes", "label", "built_s", "measured")

    def __init__(self, value, nbytes, label, built_s,
                 measured=False):
        self.value = value
        self.nbytes = nbytes
        self.label = label
        self.built_s = built_s
        # True when nbytes is a MEASURED serialized size (obs/costs)
        # rather than the flat per-entry fallback estimate.
        self.measured = measured


class CompiledProgramCache:
    """LRU cache of compiled-program callables with build coalescing.

    ``max_entries <= 0`` disables caching entirely (every lookup
    builds).  ``max_bytes`` bounds the *estimated* resident size: jax
    exposes no portable executable-size API, so each entry charges
    ``entry_bytes`` (config-tunable) unless the caller provides a
    better estimate — the cap is a safety valve against unbounded
    program diversity, not an exact accountant.
    """

    def __init__(
        self,
        max_entries: int = 64,
        max_bytes: int = 2 << 30,
        entry_bytes: int = 32 << 20,
    ):
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.entry_bytes = int(entry_bytes)
        self._entries: OrderedDict[str, _Entry] = OrderedDict()
        self._building: dict[str, threading.Event] = {}
        self._lock = make_lock("CompiledProgramCache._lock")
        self._devices: tuple | None = None
        # Bumped on every device-set clear: a build that STARTED
        # before an invalidation must not be inserted after it (its
        # trace may pin handles into the dead device set).
        self._generation = 0
        # Fired (under the cache lock — keep them fast, never call
        # back into the cache) when the device-set check clears the
        # cache, so dependent state (the engine's warm-start hints)
        # doesn't keep claiming programs are compiled.
        self._invalidation_listeners: list[Callable[[], None]] = []
        # Counters (process lifetime; ``stats()`` snapshots them).
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.coalesced = 0
        self.invalidations = 0
        self.trace_time_s = 0.0

    # -- internals ----------------------------------------------------------

    def _bytes_locked(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def _check_devices_locked(self) -> None:
        sig = _device_signature()
        if self._devices is None:
            self._devices = sig
            return
        if sig != self._devices:
            # Every cached executable pins handles into the OLD device
            # set — running one would crash or silently target dead
            # devices.  Drop them all; the next jobs re-trace.
            if self._entries:
                self.invalidations += 1
            self._entries.clear()
            self._devices = sig
            self._generation += 1
            for listener in self._invalidation_listeners:
                try:
                    listener()
                except Exception:  # noqa: BLE001 — never break a lookup
                    pass

    def _evict_locked(self) -> None:
        while self._entries and (
            len(self._entries) > self.max_entries
            or self._bytes_locked() > self.max_bytes
        ):
            if len(self._entries) == 1:
                break  # never evict the entry just inserted
            self._entries.popitem(last=False)
            self.evictions += 1

    # -- public surface -----------------------------------------------------

    def get_or_build(
        self,
        key: str,
        builder: Callable[[], Any],
        *,
        label: str | None = None,
        nbytes: int | None = None,
    ) -> Any:
        """Return the cached program for ``key``, building it (once,
        even under concurrent callers) on a miss."""
        if self.max_entries <= 0:
            with self._lock:
                self.misses += 1
            t0 = time.perf_counter()
            _faults().hit("compile.build")
            value = builder()
            built_s = time.perf_counter() - t0
            _record_compile_span(built_s, label, key)
            _flight().record(
                "compile", "build",
                key=key, label=label or "", builtS=round(built_s, 4),
            )
            self._note_cost(key, label, built_s)
            return value
        while True:
            with self._lock:
                self._check_devices_locked()
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    value = entry.value
                    if type(value) is _AOTRestored:
                        # A pre-warmed executable has no rebuild path
                        # yet; arm its call-time fallback with this
                        # caller's builder.
                        value.bind_builder(builder)
                    return value
                pending = self._building.get(key)
                if pending is None:
                    pending = self._building[key] = threading.Event()
                    build_generation = self._generation
                    break
            # Another thread is tracing this exact program right now
            # (tune candidates submit together): wait for it rather
            # than racing a duplicate trace, then re-check — a hit if
            # it succeeded, our turn to build if it raised.
            pending.wait()
            with self._lock:
                if key in self._entries:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    self.coalesced += 1
                    value = self._entries[key].value
                    if type(value) is _AOTRestored:
                        value.bind_builder(builder)
                    return value
        t0 = time.perf_counter()
        restored = None
        try:
            # Durable warm start: a persisted AOT executable satisfies
            # the miss without tracing OR compiling (train/aot_store.py
            # validates headers/checksums; any mismatch returns None
            # and the live build below proceeds as if no store existed).
            restored = self._aot_restore(key, builder, label)
            if restored is None:
                # Chaos probe on the BUILD path only: cache hits must
                # stay untouched (a compile fault models tracing/XLA
                # failure, which by definition happens when a program
                # builds).
                _faults().hit("compile.build")
                value = builder()
            else:
                value = restored[0]
        except BaseException:
            with self._lock:
                ev = self._building.pop(key, None)
            if ev is not None:
                ev.set()
            raise
        built_s = time.perf_counter() - t0
        if restored is None:
            # An AOT-satisfied lookup records NO compile span — the
            # restart drill asserts pre-warmed keys rebuild nothing.
            _record_compile_span(built_s, label, key)
        _flight().record(
            "compile",
            "build" if restored is None else "aot_restore",
            key=key, label=label or "", builtS=round(built_s, 4),
        )
        self._note_cost(key, label, built_s)
        measured = False
        if nbytes is None:
            if restored is not None:
                # The store's manifest carries the blob's measured size.
                nbytes = restored[1]
                measured = nbytes is not None
            else:
                # Real serialized size when the builder's cost analysis
                # measured one (ROADMAP item 3's carried debt: the byte
                # cap charged a flat 32 MiB per entry); the flat
                # estimate survives only as the fallback for unanalyzed
                # programs.
                nbytes = self._measured_bytes(key)
                measured = nbytes is not None
        with self._lock:
            ev = self._building.pop(key, None)
            self.misses += 1
            if restored is None:
                self.trace_time_s += built_s
            if build_generation == self._generation:
                self._entries[key] = _Entry(
                    value,
                    self.entry_bytes if nbytes is None else int(nbytes),
                    label,
                    built_s,
                    measured=measured,
                )
                self._entries.move_to_end(key)
                self._evict_locked()
            # else: the device set changed while this build was in
            # flight — the program may pin handles into the dead set;
            # hand it to THIS caller only (it fails fast if devices
            # really died) and never cache it.
        if ev is not None:
            ev.set()
        return value

    @staticmethod
    def _aot_restore(key: str, builder, label):
        """``(guarded_value, nbytes|None)`` from the durable AOT store,
        or None → build live.  Never raises except the fault plane's
        ``Preempted`` (the store re-raises it: preemption belongs to
        the job retry loop, not the corruption fallback)."""
        try:
            store = _aot().get_store()
        except Exception:  # noqa: BLE001 — a broken store must never
            return None  # break the build path it shortcuts
        if store is None:
            return None
        compiled = store.load(key)
        if compiled is None:
            return None
        rec = store.entry(key) or {}
        return (
            _AOTRestored(compiled, builder, key, label),
            rec.get("bytes"),
        )

    def install(self, key: str, value, *, label: str | None = None,
                nbytes: int | None = None) -> bool:
        """Install an externally restored program (boot pre-warm,
        services/context.py) WITHOUT counting a hit or miss and without
        recording a compile span.  Respects the device-set check and
        the eviction policy; an already-resident key wins (never
        clobber a live entry).  Returns True when the key is resident
        afterwards."""
        if self.max_entries <= 0:
            return False
        with self._lock:
            self._check_devices_locked()
            if key in self._entries:
                self._entries.move_to_end(key)
                return True
            self._entries[key] = _Entry(
                value,
                self.entry_bytes if nbytes is None else int(nbytes),
                label,
                0.0,
                measured=nbytes is not None,
            )
            self._entries.move_to_end(key)
            self._evict_locked()
            return key in self._entries

    @staticmethod
    def _note_cost(key: str, label, built_s: float) -> None:
        """Every build — cached or not, analyzed or not — lands a
        ProgramCost ledger entry (obs/costs.py).  Never fails a
        build."""
        try:
            _costs().note_build(key, label, built_s)
        except Exception:  # noqa: BLE001
            pass

    @staticmethod
    def _measured_bytes(key: str):
        try:
            return _costs().serialized_bytes(key)
        except Exception:  # noqa: BLE001
            return None

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    def add_invalidation_listener(self, listener: Callable[[], None]):
        """Register a callback fired when a device-set change clears
        the cache.  Runs under the cache lock: must be fast and must
        not call back into the cache.  Pair with
        :meth:`remove_invalidation_listener` on owner teardown."""
        with self._lock:
            self._invalidation_listeners.append(listener)

    def remove_invalidation_listener(self, listener) -> None:
        with self._lock:
            try:
                self._invalidation_listeners.remove(listener)
            except ValueError:
                pass

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats(self) -> dict:
        """Counter snapshot for the monitoring endpoint / tfevents."""
        with self._lock:
            return {
                "entries": len(self._entries),
                "maxEntries": self.max_entries,
                "bytesEstimate": self._bytes_locked(),
                "maxBytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "coalesced": self.coalesced,
                "deviceInvalidations": self.invalidations,
                "traceTimeS": round(self.trace_time_s, 4),
                "measuredEntries": sum(
                    1 for e in self._entries.values() if e.measured
                ),
                "programs": [
                    e.label for e in self._entries.values() if e.label
                ],
                # Per-entry accounting: what each resident program
                # charges the byte cap, and whether that charge is a
                # measured serialized size or the flat fallback.
                "entries_detail": [
                    {
                        "key": key[:12],
                        "label": e.label,
                        "bytes": e.nbytes,
                        "measured": e.measured,
                        "builtS": round(e.built_s, 4),
                    }
                    for key, e in self._entries.items()
                ],
            }


# -- process-wide singleton ---------------------------------------------------

_cache: CompiledProgramCache | None = None
_cache_lock = make_lock("compile_cache._cache_lock")


def get_cache() -> CompiledProgramCache:
    """The process-wide cache, sized from config (LO_TPU_COMPILE_CACHE_*)."""
    global _cache
    with _cache_lock:
        if _cache is None:
            from learningorchestra_tpu.config import get_config

            cc = get_config().compile_cache
            _cache = CompiledProgramCache(
                max_entries=cc.max_entries,
                max_bytes=cc.max_bytes,
                entry_bytes=cc.entry_bytes,
            )
        return _cache


def reset_cache(**overrides) -> CompiledProgramCache:
    """Replace the singleton (tests; or re-size after a config change)."""
    global _cache
    with _cache_lock:
        if overrides:
            _cache = CompiledProgramCache(**overrides)
            return _cache
        _cache = None
    return get_cache()  # rebuild from config OUTSIDE the lock


# -- per-job accounting helpers ----------------------------------------------

_COUNTER_KEYS = ("hits", "misses", "evictions", "coalesced", "traceTimeS")


def enabled() -> bool:
    """False when the operator disabled caching
    (LO_TPU_COMPILE_CACHE_ENTRIES=0) — callers publishing warm-start
    hints must not claim programs are cached when nothing ever is."""
    return get_cache().max_entries > 0


def counters_snapshot() -> dict:
    stats = get_cache().stats()
    return {k: stats[k] for k in _COUNTER_KEYS}


def delta_since(before: dict) -> dict:
    """Counter delta for one job.  Counters are process-wide, so under
    concurrent jobs a delta attributes overlapping activity — exact for
    serial submissions, an upper bound otherwise."""
    now = counters_snapshot()
    out = {k: now[k] - before.get(k, 0) for k in _COUNTER_KEYS}
    out["traceTimeS"] = round(out["traceTimeS"], 4)
    return out
