"""Training layer: jitted loops with the keras-``fit`` contract."""

from learningorchestra_tpu.train.neural import NeuralEstimator, TrainHistory

__all__ = ["NeuralEstimator", "TrainHistory"]
