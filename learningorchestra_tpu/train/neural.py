"""NeuralEstimator — keras-``fit`` semantics over a jitted JAX train loop.

The reference trains keras models by calling ``model.fit(**params)`` inside
a Flask worker, with epochs/batch_size/validation_split/callbacks arriving
as request JSON (reference: microservices/binary_executor_image/
training_function/train_function.py:84-87, binary_execution.py:188-200).
This class accepts the same request shape but executes TPU-first:

- the loss/grad/update step is a single jitted function; an epoch is one
  `lax.scan` over pre-batched device-resident data — zero host round-trips
  per step (the reference pays Python dispatch per batch);
- parameters and optimizer state live in HBM between epochs; host sees them
  only at checkpoint boundaries (`jax.device_get` at job edges, SURVEY §5.4);
- compute dtype is bfloat16 by default on TPU (MXU-native), params fp32;
- the distributed (mesh-sharded) training path lives in
  ``learningorchestra_tpu.parallel`` — it reuses these loss definitions and
  shards the batch axis so XLA inserts the gradient all-reduce over ICI
  (replacing Horovod's host-side ring, reference: train_function.py:55-61).
"""

from __future__ import annotations

import functools
import re
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from learningorchestra_tpu.jobs.cancel import cancel_requested
from learningorchestra_tpu.obs import tracing as obs_tracing
from learningorchestra_tpu.toolkit.base import Estimator, as_array


def _train_logger():
    from learningorchestra_tpu.log import get_logger

    return get_logger("train")


def _faults():
    """Lazy fault-plane handle (the ``train.epoch`` chaos probe)."""
    from learningorchestra_tpu import faults

    return faults


def _spec_get(spec: dict, snake: str, default=None, *, required=False):
    """Read a spec key in snake_case OR camelCase — REST bodies use
    camelCase (vocabSize, maxLen) while Python callers write snake."""
    camel = re.sub(r"_(\w)", lambda m: m.group(1).upper(), snake)
    for key in (snake, camel):
        if key in spec:
            return spec[key]
    if required:
        raise ValueError(f"learning-rate schedule needs {snake!r}")
    return default


def resolve_learning_rate(lr):
    """A float passes through; a dict becomes an optax schedule.

    JSON-expressible schedules so the REST surface (model
    classParameters / train compile bodies, services/model.py) can
    configure warmup and decay without shipping Python — the
    reference's wrapped keras models took schedules via compile_code
    (reference: binary_executor_image/training_function/
    train_function.py:75-82); here the same knob is declarative:

        {"schedule": "warmup_cosine", "peakValue": 3e-4,
         "warmupSteps": 500, "decaySteps": 10000}

    Kinds: constant, warmup_cosine, cosine, exponential, piecewise.
    Steps are optimizer steps (one per batch), the optax convention.
    """
    if not isinstance(lr, dict):
        return float(lr)
    kind = str(lr.get("schedule", "")).lower()
    if kind in ("warmup_cosine", "warmupcosine"):
        peak = float(_spec_get(lr, "peak_value", required=True))
        return optax.warmup_cosine_decay_schedule(
            init_value=float(_spec_get(lr, "init_value", 0.0)),
            peak_value=peak,
            warmup_steps=int(_spec_get(lr, "warmup_steps", required=True)),
            decay_steps=int(_spec_get(lr, "decay_steps", required=True)),
            end_value=float(_spec_get(lr, "end_value", 0.0)),
        )
    if kind == "cosine":
        return optax.cosine_decay_schedule(
            init_value=float(_spec_get(lr, "init_value", required=True)),
            decay_steps=int(_spec_get(lr, "decay_steps", required=True)),
            alpha=float(_spec_get(lr, "alpha", 0.0)),
        )
    if kind == "exponential":
        return optax.exponential_decay(
            init_value=float(_spec_get(lr, "init_value", required=True)),
            transition_steps=int(
                _spec_get(lr, "transition_steps", required=True)
            ),
            decay_rate=float(_spec_get(lr, "decay_rate", required=True)),
            staircase=bool(_spec_get(lr, "staircase", False)),
        )
    if kind == "piecewise":
        # JSON object keys are strings; optax wants {int step: scale}.
        raw = _spec_get(lr, "boundaries_and_scales", required=True)
        return optax.piecewise_constant_schedule(
            init_value=float(_spec_get(lr, "init_value", required=True)),
            boundaries_and_scales={
                int(k): float(v) for k, v in dict(raw).items()
            },
        )
    if kind == "constant":
        return float(_spec_get(lr, "value", required=True))
    raise ValueError(
        f"unknown learning-rate schedule {lr.get('schedule')!r}; "
        "expected warmup_cosine | cosine | exponential | piecewise | "
        "constant"
    )


_OPTIMIZER_FACTORIES = {
    name: getattr(optax, name)
    for name in ("adam", "adamw", "sgd", "rmsprop", "adagrad", "lamb",
                 "lion", "novograd", "radam")
    if hasattr(optax, name)
}


def resolve_optimizer(optimizer, learning_rate=1e-3):
    """Turn a REST-expressible optimizer spec into an optax transform.

    ``optimizer`` may be: None (adam at ``learning_rate``), an optax
    object (passed through), a name string ("sgd"), or a dict
    ``{"name": "adamw", "learningRate": ..., "weightDecay": 1e-2}`` —
    extra keys forward to the optax factory (snake or camelCase); the
    learning rate itself may be a schedule spec
    (:func:`resolve_learning_rate`).
    """
    if optimizer is None:
        return optax.adam(resolve_learning_rate(learning_rate))
    if isinstance(optimizer, str):
        optimizer = {"name": optimizer}
    if not isinstance(optimizer, dict):
        return optimizer  # already an optax GradientTransformation
    spec = dict(optimizer)
    name = str(spec.pop("name", "") or "").lower()
    factory = _OPTIMIZER_FACTORIES.get(name)
    if factory is None:
        raise ValueError(
            f"unknown optimizer {name!r}; expected one of "
            f"{sorted(_OPTIMIZER_FACTORIES)}"
        )
    lr = None
    for key in ("learning_rate", "learningRate"):
        if key in spec:
            lr = spec.pop(key)
    if lr is None:
        lr = learning_rate
    kwargs = {
        re.sub(r"([A-Z])", lambda m: "_" + m.group(1).lower(), k): v
        for k, v in spec.items()
    }
    return factory(resolve_learning_rate(lr), **kwargs)


class TrainHistory(dict):
    """keras-History-shaped: {"loss": [...], "accuracy": [...], ...}."""

    def append(self, metrics: dict) -> None:
        for key, val in metrics.items():
            self.setdefault(key, []).append(float(val))


def build_stop_callbacks(owner, callbacks, early_stopping) -> list:
    """Shared fit-surface plumbing: normalize the callback list, fold
    in an ``early_stopping`` spec, reset reused EarlyStopping
    instances, and clear ``owner.stop_training``.  Every fit surface
    supports ``restoreBestWeights`` now: single-device and mesh-
    sharded fits snapshot device-side with sharding preserved
    (parallel/distributed.py), and stage-partitioned pipeline state
    snapshots leaf-by-leaf with each leaf's own placement preserved
    (:func:`snapshot_params`)."""
    owner.stop_training = False
    cbs = list(callbacks or [])
    # False is the natural JSON off-toggle mirroring True — disabled,
    # not a TypeError deep in from_spec.
    if early_stopping is not None and early_stopping is not False:
        cbs.append(EarlyStopping.from_spec(early_stopping))
    for cb in cbs:
        if isinstance(cb, EarlyStopping):
            cb.reset()
    return cbs


_SNAPSHOT_FN = None


def snapshot_params(params):
    """Device-side copy of a parameter tree for best-weights rollback.

    Eager ``jnp.copy`` rejects non-fully-addressable arrays (a
    multi-host mesh's fsdp/tp shards live on other hosts), so the copy
    runs under one cached jit PER LEAF: each leaf copies following its
    own sharding/placement, which covers host numpy trees,
    single-device arrays, global sharded arrays — and stage-PARTITIONED
    pipeline trees whose leaves are committed to different devices (a
    single whole-tree jit would reject a computation spanning devices;
    leaf-wise, every stage's weights snapshot on their own chip).
    Every process of a multi-controller fit issues the same calls in
    the same order (callbacks run the same loop on every host), the
    SPMD requirement.
    """
    global _SNAPSHOT_FN
    if _SNAPSHOT_FN is None:
        _SNAPSHOT_FN = jax.jit(jnp.copy)
    return jax.tree_util.tree_map(_SNAPSHOT_FN, params)


class EarlyStopping:
    """Keras-parity early stopping, usable as a fit callback or (as a
    JSON dict via the REST train surface) the ``early_stopping`` fit
    parameter — the reference's wrapped keras models took this via
    callback code strings (reference: binary_executor_image/
    training_function/train_function.py:75-87).

    ``monitor=None`` picks ``val_loss`` when validation runs, else
    ``loss``.  ``mode="auto"`` minimizes unless the metric name looks
    like accuracy/F1.  ``restore_best_weights=True`` snapshots the best
    epoch's params (a device-side copy — the epoch runner donates its
    input buffers, so holding a live reference would dangle)."""

    def __init__(self, monitor: str | None = None, patience: int = 0,
                 min_delta: float = 0.0, mode: str = "auto",
                 restore_best_weights: bool = False, baseline=None):
        if mode not in ("auto", "min", "max"):
            raise ValueError(f"mode must be auto|min|max, got {mode!r}")
        self.monitor = monitor
        self.patience = int(patience)
        self.min_delta = abs(float(min_delta))
        self.mode = mode
        self.restore_best_weights = bool(restore_best_weights)
        self.baseline = baseline
        self.reset()

    def reset(self) -> None:
        """Clear per-run state — fit() calls this at train start so a
        reused instance doesn't carry best/wait (or a stale best-params
        snapshot) from a previous fit into a new one."""
        self.best = None
        self.best_params = None
        self.best_epoch = None
        self.wait = 0
        self._warned_missing = False

    @classmethod
    def from_spec(cls, spec) -> "EarlyStopping":
        """Build from a REST-JSON dict (snake_case or camelCase)."""
        if isinstance(spec, cls):
            return spec
        if spec is True:
            return cls()
        spec = dict(spec)
        kw = {}
        for snake in ("monitor", "patience", "min_delta", "mode",
                      "restore_best_weights", "baseline"):
            val = _spec_get(spec, snake)
            if val is not None:
                kw[snake] = val
        return cls(**kw)

    def _resolve(self, metrics: dict) -> tuple[str, bool]:
        name = self.monitor or (
            "val_loss" if "val_loss" in metrics else "loss"
        )
        if self.mode != "auto":
            minimize = self.mode == "min"
        else:
            minimize = not any(
                tag in name for tag in ("acc", "f1", "auc", "precision",
                                        "recall")
            )
        return name, minimize

    def __call__(self, epoch: int, metrics: dict, model) -> None:
        name, minimize = self._resolve(metrics)
        if name not in metrics:
            # e.g. val_loss requested but no validation ran.  Warn once
            # (keras parity): a silent no-op reads as a broken callback
            # when training then runs every epoch (ADVICE r3).
            if not self._warned_missing:
                self._warned_missing = True
                _train_logger().warning(
                    "EarlyStopping monitor %r not in metrics %s — "
                    "early stopping is inactive this fit",
                    name, sorted(metrics),
                )
            return
        value = float(metrics[name])
        if self.best is None and self.baseline is not None:
            # keras semantics: with a baseline, the first "best" to beat
            # is the baseline itself, not the first epoch's value.
            self.best = float(self.baseline)
        improved = (
            self.best is None
            or (value < self.best - self.min_delta if minimize
                else value > self.best + self.min_delta)
        )
        if improved:
            self.best, self.best_epoch, self.wait = value, epoch, 0
            if self.restore_best_weights:
                self.best_params = snapshot_params(model.params)
        else:
            self.wait += 1
        # keras parity: patience=N stops after N consecutive
        # non-improving epochs (patience=0 → the first one).
        if self.wait >= max(1, self.patience):
            model.stop_training = True
            if self.restore_best_weights and self.best_params is not None:
                model.params = self.best_params
                model.opt_state = None  # moments belong to later epochs


def _batch_data(x: np.ndarray, y: np.ndarray, batch_size: int, rng):
    """Shuffle + pad to a whole number of batches; returns (xb, yb, mask)
    with shapes (n_batches, bs, ...).  Padding rows carry mask 0 so metrics
    and gradients ignore them — keras parity without dropping remainders."""
    n = x.shape[0]
    if n == 0:
        raise ValueError("cannot batch an empty dataset")
    perm = rng.permutation(n)
    n_batches = max(1, -(-n // batch_size))
    pad = n_batches * batch_size - n
    # np.resize cycles perm, so pad may exceed n (tiny datasets).
    idx = np.concatenate([perm, np.resize(perm, pad)]) if pad else perm
    mask = np.ones(n_batches * batch_size, np.float32)
    if pad:
        mask[n:] = 0.0
    xb = x[idx].reshape(n_batches, batch_size, *x.shape[1:])
    yb = y[idx].reshape(n_batches, batch_size, *y.shape[1:])
    mb = mask.reshape(n_batches, batch_size)
    return xb, yb, mb


def _apply_with_aux(module, p, xb):
    """Apply the module collecting sown auxiliary losses.

    Modules may ``sow('losses', name, value)`` extra differentiable
    objective terms (the MoE load-balancing loss, ops/moe.py); dense
    modules sow nothing and the collection comes back empty.  Returns
    ``(f32 logits, f32 aux-loss sum)``.
    """
    logits, var = module.apply(p, xb, mutable="losses")
    aux = jnp.asarray(0.0, jnp.float32)
    for leaf in jax.tree_util.tree_leaves(var):
        aux = aux + jnp.sum(leaf).astype(jnp.float32)
    return logits.astype(jnp.float32), aux


def _is_sharded(obj) -> bool:
    """True for sharded-dataset handles/views (and tuples holding one)
    — the dispatch predicate for the streaming fit/evaluate paths."""
    from learningorchestra_tpu.store import sharded as sh

    if isinstance(obj, (sh.ShardedDataset, sh.ShardedView)):
        return True
    if isinstance(obj, tuple):
        return any(_is_sharded(o) for o in obj)
    return False


def _finalize_metrics(metrics):
    """Batch-mean the stacked per-step metrics, then apply the
    post-reduction transforms: 'perplexity' arrives as raw per-token CE
    and becomes exp(mean CE) — exactly exp of the reported loss."""
    out = jax.tree_util.tree_map(jnp.mean, metrics)
    if "perplexity" in out:
        out["perplexity"] = jnp.exp(out["perplexity"])
    return out


def _param_cast_for(dtype):
    """Mixed precision, the TPU-standard way: the OPTIMIZER holds f32
    master weights; the forward/backward run on a low-precision COPY of
    the params cast inside the objective (so the cast is part of the
    differentiated graph and grads come back f32).

    Casting inputs alone is not enough: flax modules with
    ``dtype=None`` promote inputs against their f32 params, which
    silently pins every matmul to f32 — half MXU rate.  The MoE router
    is exempted below (full-precision weights); it also declares an
    explicit f32 compute dtype in ops/moe.py.
    """
    if dtype is None:
        return lambda p: p

    def _leaf(path, l):
        # The MoE router must see full-precision WEIGHTS, not just f32
        # compute (ops/moe.py design note: bf16-rounded router kernels
        # flip near-tied top-k choices).
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        ).lower()
        if "router" in name:
            return l
        return l.astype(dtype) if l.dtype == jnp.float32 else l

    def cast(p):
        return jax.tree_util.tree_map_with_path(_leaf, p)

    return cast


def _device_epoch_raw(
    module, optimizer, loss_fn, dtype, *, n, batch_size, shuffle
):
    """Unjitted whole-epoch function over a device-resident dataset —
    shared by the per-epoch runner (jitted directly) and the fused
    multi-epoch runner (scanned)."""
    n_batches = max(1, -(-n // batch_size))
    pad = n_batches * batch_size - n
    _pcast = _param_cast_for(dtype)
    _cast = _cast_for(dtype)

    def epoch(params, opt_state, x, y, key):
        order = (
            jax.random.permutation(key, n) if shuffle else jnp.arange(n)
        )
        if pad:
            # np.resize-style cycling so tiny datasets (pad > n) work.
            extra = jnp.resize(order, (pad,))
            idx = jnp.concatenate([order, extra])
        else:
            idx = order
        mask = jnp.concatenate(
            [jnp.ones(n, jnp.float32), jnp.zeros(pad, jnp.float32)]
        )
        xb = x[idx].reshape(n_batches, batch_size, *x.shape[1:])
        yb = y[idx].reshape(n_batches, batch_size, *y.shape[1:])
        mb = mask.reshape(n_batches, batch_size)

        def body(carry, batch):
            params, opt_state = carry
            bx, by, bm = batch

            def objective(p):
                logits, aux = _apply_with_aux(
                    module, _pcast(p), _cast(bx)
                )
                loss, metrics = loss_fn(logits, by, bm)
                return loss + aux, metrics

            grads, metrics = jax.grad(objective, has_aux=True)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), metrics

        (params, opt_state), metrics = jax.lax.scan(
            body, (params, opt_state), (xb, yb, mb)
        )
        return params, opt_state, _finalize_metrics(metrics)

    return epoch


def build_device_epoch(
    module, optimizer, loss_fn, dtype, *, n, batch_size, shuffle
):
    """Jitted whole-epoch step over a DEVICE-RESIDENT dataset.

    The dataset is uploaded once; each epoch is one jitted call that
    permutes indices on device (``jax.random.permutation``), gathers
    batches in HBM and scans the train step — host traffic per epoch is
    one PRNG key and the metrics scalars, vs. the host-side reshuffle +
    full re-upload per epoch of the generic path (the reference pays
    keras' per-batch Python dispatch on top, train_function.py:84-87).
    (params, opt_state) are donated so updates happen in place.
    """
    epoch = _device_epoch_raw(
        module, optimizer, loss_fn, dtype,
        n=n, batch_size=batch_size, shuffle=shuffle,
    )
    return jax.jit(epoch, donate_argnums=(0, 1))


def build_fused_epochs(
    module, optimizer, loss_fn, dtype, *, n, batch_size, shuffle, epochs
):
    """ALL epochs in one jitted call: ``lax.scan`` over the device
    epoch, per-epoch keys folded in on device, metrics stacked and read
    back once at the end.

    This exists for high-dispatch-latency links (the remote-TPU tunnel
    pays ~10-100 ms per dispatch/readback): the per-epoch runner costs
    one round-trip per epoch, which dominates sub-100 ms epochs and
    corrupts throughput measurements; here K epochs cost exactly one.
    No per-epoch host work is possible inside (checkpointing/verbose
    callbacks need the per-epoch runner).
    """
    epoch_raw = _device_epoch_raw(
        module, optimizer, loss_fn, dtype,
        n=n, batch_size=batch_size, shuffle=shuffle,
    )

    def fused(params, opt_state, x, y, key):
        def body(carry, e):
            params, opt_state = carry
            params, opt_state, metrics = epoch_raw(
                params, opt_state, x, y, jax.random.fold_in(key, e)
            )
            return (params, opt_state), metrics

        (params, opt_state), metrics = jax.lax.scan(
            body, (params, opt_state), jnp.arange(epochs)
        )
        return params, opt_state, metrics  # metrics: (epochs,) per key

    return jax.jit(fused, donate_argnums=(0, 1))


def _cast_for(dtype):
    def _cast(xb):
        return (
            xb.astype(dtype)
            if dtype and jnp.issubdtype(xb.dtype, jnp.floating)
            else xb
        )

    return _cast


def _cached_program(
    kind: str, est, loss_kind, *, shapes=None, mesh=None, donate=None,
    builder, cost_args=None, want_cost=False,
):
    """Fetch (or build-once) a jitted program through the process-wide
    compiled-program cache (train/compile_cache.py), keyed by the
    estimator's architecture/optimizer/loss/dtype spec plus whatever
    the builder bakes into the trace.  Repeat REST jobs and
    same-architecture tune candidates skip tracing entirely.

    ``cost_args`` (a thunk returning example arguments) lets the
    build-once path run XLA cost/memory analysis on the freshly built
    program (obs/costs.py) — shape avatars only, nothing touches real
    buffers.  Builders returning a TUPLE of jitted callables (the
    (epoch, evaluate) pairs the mesh-sharded paths build) probe their
    FIRST element — the epoch program, the one that dominates device
    time.  ``want_cost=True`` returns ``(fn, ProgramCost | None)``
    so dispatch sites can attribute device time with flops attached."""
    from learningorchestra_tpu.train import compile_cache as cc

    key = cc.program_key(
        kind,
        module=cc.module_fingerprint(est.module),
        optimizer=cc.optimizer_fingerprint(est),
        loss=loss_kind,
        dtype=est.compute_dtype,
        shapes=shapes,
        mesh=mesh,
        donate=donate,
    )
    label = f"{kind}:{type(est.module).__name__}"
    building = builder
    if cost_args is not None:
        def building():
            fn = builder()
            target = fn[0] if isinstance(fn, tuple) else fn
            # Tuple-valued builders (epoch, evaluate) are not AOT-
            # eligible: a restored single executable couldn't stand in
            # for the pair the consumers unpack.
            _probe_program_cost(
                key, label, target, cost_args,
                aot_eligible=not isinstance(fn, tuple),
            )
            return fn

    fn = cc.get_cache().get_or_build(key, building, label=label)
    if not want_cost:
        return fn
    from learningorchestra_tpu.obs import costs as obs_costs

    return fn, (
        obs_costs.get_ledger().get(key)
        if obs_costs.enabled() else None
    )


def _probe_program_cost(key, label, fn, cost_args, *,
                        aot_eligible: bool = True,
                        collectives_excluded: bool = False) -> None:
    """Best-effort XLA cost analysis for a just-built program; a
    failed probe (opaque callable, exotic arg tree) must never fail
    the build it rides.  ``collectives_excluded=True`` marks probes
    whose lowering is collective-free by construction (single-device
    MPMD stage programs, host-avatar serve probes) so downstream MFU
    math knows the flops are pure compute."""
    from learningorchestra_tpu.obs import costs as obs_costs

    if not obs_costs.enabled():
        return
    try:
        obs_costs.analyze_jitted(
            key, label, fn, tuple(cost_args()),
            aot_eligible=aot_eligible,
            collectives_excluded=collectives_excluded,
        )
    except Exception:  # noqa: BLE001
        pass


def _attribute_epoch_cost(est, epoch_s: float) -> None:
    """One epoch's device interval into the per-job device-time ledger
    (the job identity rides the executor's ``costs.job_scope``)."""
    from learningorchestra_tpu.obs import costs as obs_costs

    if not obs_costs.enabled():
        return
    try:
        obs_costs.attribute(
            epoch_s, cost=getattr(est, "_device_epoch_cost", None)
        )
    except Exception:  # noqa: BLE001 — accounting never fails a fit
        pass


def _epoch_cost_attrs(est, epoch_s: float) -> dict:
    """flops/bytes/MFU span annotations for one epoch, empty when the
    program was never analyzed (CPU fallback, costs disabled)."""
    from learningorchestra_tpu.obs import costs as obs_costs

    cost = getattr(est, "_device_epoch_cost", None)
    if cost is None or not getattr(cost, "analyzed", False):
        return {}
    attrs: dict = {}
    if cost.flops is not None:
        attrs["flops"] = cost.flops
    if cost.bytes_accessed is not None:
        attrs["bytesAccessed"] = cost.bytes_accessed
    try:
        util = obs_costs.mfu(
            cost.flops or 0.0, epoch_s,
            peak_flops=obs_costs.peak_flops(),
        )
    except Exception:  # noqa: BLE001
        util = None
    if util is not None:
        attrs["mfu"] = util
    return attrs


def cached_fused_epochs(
    est, loss_kind, *, n, batch_size, shuffle, epochs
):
    """Cache-fronted :func:`build_fused_epochs` — the bench's cold/warm
    probe and any repeated fused-epoch caller share one trace per
    (arch, optimizer, loss, dtype, shape, epochs) tuple."""
    dtype = jnp.bfloat16 if est.compute_dtype == "bfloat16" else None
    return _cached_program(
        "fused_epochs", est, loss_kind,
        shapes=(n, batch_size, bool(shuffle), int(epochs)),
        builder=lambda: build_fused_epochs(
            est.module, est.optimizer, est._loss_and_metrics(loss_kind),
            dtype, n=n, batch_size=batch_size, shuffle=bool(shuffle),
            epochs=int(epochs),
        ),
    )


def _make_step(module, optimizer, loss_fn, _cast, _pcast):
    def step(params, opt_state, xb, yb, mb):
        def objective(p):
            logits, aux = _apply_with_aux(module, _pcast(p), _cast(xb))
            loss, metrics = loss_fn(logits, yb, mb)
            return loss + aux, metrics

        grads, metrics = jax.grad(objective, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    return step


def build_epoch_fns(module, optimizer, loss_fn, dtype, *, donate=False):
    """Jitted (epoch, evaluate) pair shared by the single-device and
    mesh-sharded training paths — the loss/grad/update math exists once.

    ``donate=True`` donates the (params, opt_state) carry so updates
    happen in place in HBM (the distributed path's steady state).
    """
    _cast = _cast_for(dtype)
    _pcast = _param_cast_for(dtype)
    step = _make_step(module, optimizer, loss_fn, _cast, _pcast)

    def epoch(params, opt_state, xs, ys, ms):
        def body(carry, batch):
            params, opt_state = carry
            params, opt_state, metrics = step(params, opt_state, *batch)
            return (params, opt_state), metrics

        (params, opt_state), metrics = jax.lax.scan(
            body, (params, opt_state), (xs, ys, ms)
        )
        return params, opt_state, _finalize_metrics(metrics)

    def evaluate(params, xs, ys, ms):
        params = _pcast(params)  # same numerics (and MXU rate) as train

        def body(_, batch):
            xb, yb, mb = batch
            logits = module.apply(params, _cast(xb)).astype(jnp.float32)
            return None, loss_fn(logits, yb, mb)[1]

        _, metrics = jax.lax.scan(body, None, (xs, ys, ms))
        return _finalize_metrics(metrics)

    return (
        jax.jit(epoch, donate_argnums=(0, 1)) if donate else jax.jit(epoch),
        jax.jit(evaluate),
    )


def build_resident_epoch_fns(
    module, optimizer, loss_fn, dtype, *, shuffle, donate=True
):
    """Jitted (epoch, evaluate) over a DEVICE-RESIDENT pre-batched
    dataset — the mesh-sharded analogue of ``build_device_epoch``.

    The (n_batches, global_bs, ...) epoch arrays are uploaded (sharded)
    once per fit; each epoch is one jitted call that permutes the BATCH
    ORDER on device from a PRNG key and scans the train step.  The batch
    axis (0) is unsharded, so the permutation gather is device-local —
    no collective, no host traffic beyond the key and the metric
    scalars.  Batch *composition* is fixed by one host-side shuffle at
    upload; per-epoch reshuffling is batch-granular (the standard
    sharded-input-pipeline trade: a sample-granular reshuffle of a
    batch-sharded array would all-gather the dataset every epoch).
    """
    _cast = _cast_for(dtype)
    _pcast = _param_cast_for(dtype)
    step = _make_step(module, optimizer, loss_fn, _cast, _pcast)

    def epoch(params, opt_state, xs, ys, ms, key):
        nb = xs.shape[0]
        order = (
            jax.random.permutation(key, nb) if shuffle else jnp.arange(nb)
        )

        # Scan over the permuted INDEX vector, gathering one batch per
        # step: a whole-dataset jnp.take would materialize a second
        # full-size copy and double peak HBM — defeating the point of
        # keeping the dataset resident.
        def body(carry, i):
            params, opt_state = carry
            params, opt_state, metrics = step(
                params, opt_state, xs[i], ys[i], ms[i]
            )
            return (params, opt_state), metrics

        (params, opt_state), metrics = jax.lax.scan(
            body, (params, opt_state), order
        )
        return params, opt_state, _finalize_metrics(metrics)

    def evaluate(params, xs, ys, ms):
        params = _pcast(params)  # same numerics (and MXU rate) as train

        def body(_, batch):
            xb, yb, mb = batch
            logits = module.apply(params, _cast(xb)).astype(jnp.float32)
            return None, loss_fn(logits, yb, mb)[1]

        _, metrics = jax.lax.scan(body, None, (xs, ys, ms))
        return _finalize_metrics(metrics)

    return (
        jax.jit(epoch, donate_argnums=(0, 1)) if donate else jax.jit(epoch),
        jax.jit(evaluate),
    )


class NeuralEstimator(Estimator):
    """Wraps a Flax module with fit/evaluate/predict/save/load."""

    # The executor injects the managed checkpoint dir (and resume
    # semantics) into ``fit`` for any estimator that declares this —
    # the pipeline model mirrors the surface without subclassing.
    supports_managed_checkpoints = True

    def __init__(
        self,
        module: nn.Module,
        *,
        loss: str = "auto",  # auto | softmax_ce | sigmoid_ce | mse
        optimizer: Any = None,
        learning_rate: float = 1e-3,
        seed: int = 0,
        compute_dtype: str = "bfloat16",
    ):
        self.module = module
        self.loss = loss
        self.learning_rate = learning_rate
        self.seed = seed
        self.compute_dtype = compute_dtype
        self.optimizer = resolve_optimizer(optimizer, learning_rate)
        # Remember the declarative spec (name/dict/None=adam) so a later
        # compile(learning_rate=...) can rebuild the SAME optimizer kind;
        # an opaque optax object can't be rebuilt at a new rate.
        self._optimizer_spec = (
            optimizer if isinstance(optimizer, (str, dict))
            else ({"name": "adam"} if optimizer is None else None)
        )
        self.params = None
        self.opt_state = None
        self.stop_training = False  # callbacks may set True mid-fit
        self.history = TrainHistory()
        self._step_fn = None
        self._eval_fn = None
        self._apply_fn = None
        self._device_epoch = None
        self._device_epoch_key = None
        self._device_epoch_cost = None
        self._eval_loss_kind = None

    # -- keras-compile parity -------------------------------------------------

    def _invalidate_jit(self) -> None:
        """Drop every per-instance compiled-closure reference; the next
        fit/evaluate resolves against the current module/optimizer/loss
        configuration THROUGH the process-wide compiled-program cache
        (train/compile_cache.py) — an unchanged configuration re-binds
        the already-traced program instead of re-jitting, so this is
        cheap to call pessimistically."""
        self._step_fn = None
        self._eval_fn = None
        self._device_epoch = None
        self._device_epoch_key = None
        self._device_epoch_cost = None
        self._opt_version = getattr(self, "_opt_version", 0) + 1

    def compile(self, optimizer=None, loss: str | None = None,
                learning_rate=None, **kw) -> None:
        """Reconfigure optimizer/loss — the reference's ``compile_code``
        contract, declaratively (train_function.py:75-82).  ``optimizer``
        accepts an optax object, a name string, or a REST-JSON dict spec
        (:func:`resolve_optimizer`); ``learning_rate`` (or camelCase
        ``learningRate``) alone rebuilds the current optimizer kind at
        the new rate/schedule."""
        if learning_rate is None:
            learning_rate = kw.pop("learningRate", None)
        if optimizer is None and learning_rate is not None:
            # Rebuild the CURRENT optimizer kind at the new rate.
            # (Missing attribute = artifact pickled before this field
            # existed; those were always adam-default.)
            spec = getattr(self, "_optimizer_spec", {"name": "adam"})
            if spec is None:
                raise ValueError(
                    "current optimizer is an optax object whose rate "
                    "is baked in; pass optimizer= explicitly to "
                    "change it"
                )
            optimizer = spec
        if optimizer is not None:
            if learning_rate is not None and not isinstance(
                optimizer, (str, dict)
            ):
                raise ValueError(
                    "learning_rate is ignored for optax optimizer "
                    "objects — bake the rate into the object, or pass "
                    "a name/dict spec"
                )
            self.optimizer = resolve_optimizer(
                optimizer, learning_rate if learning_rate is not None
                else self.learning_rate,
            )
            self._optimizer_spec = (
                optimizer if isinstance(optimizer, (str, dict)) else None
            )
            if learning_rate is not None:
                self.learning_rate = learning_rate
            # A fresh base optimizer voids any accumulation wrapper and
            # any state built for the old one.
            self._base_optimizer = None
            self._accumulate_steps = 1
            if self.params is not None:
                self.opt_state = jax.jit(self.optimizer.init)(self.params)
        if loss is not None:
            self.loss = loss
        self._invalidate_jit()

    # -- loss -----------------------------------------------------------------

    def _resolve_loss(self, y: np.ndarray) -> str:
        if self.loss != "auto":
            return self.loss
        if np.issubdtype(y.dtype, np.floating) and y.ndim > 1:
            return "mse"
        if np.issubdtype(y.dtype, np.floating) and y.ndim == 1:
            return "mse"
        return "softmax_ce"

    @staticmethod
    def _loss_and_metrics(loss_kind: str) -> Callable:
        def fn(logits, y, mask):
            msum = jnp.maximum(mask.sum(), 1.0)
            if loss_kind == "softmax_ce":
                per = optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                )
                correct = (jnp.argmax(logits, -1) == y).astype(jnp.float32)
                seq_out = per.ndim == 2
                if seq_out:
                    # Sequence outputs (language models): logits
                    # (B, T, V), y (B, T) — average over NON-PAD target
                    # tokens (pad id 0, the zoo-wide convention) so a
                    # padded batch neither trains on nor scores pad
                    # positions; the per-SAMPLE mask applies unchanged.
                    tok = (y != 0).astype(jnp.float32)
                    denom = jnp.maximum(tok.sum(-1), 1.0)
                    per = (per * tok).sum(-1) / denom
                    correct = (correct * tok).sum(-1) / denom
                loss = jnp.sum(per * mask) / msum
                acc = jnp.sum(correct * mask) / msum
                metrics = {"loss": loss, "accuracy": acc}
                if seq_out:
                    # Carry the RAW per-token CE here; the epoch/eval
                    # reducers exponentiate AFTER averaging
                    # (_finalize_metrics) — exp-then-mean would report
                    # mean-of-exponentials (Jensen-biased upward) once
                    # there is more than one batch.
                    metrics["perplexity"] = loss
                return loss, metrics
            if loss_kind == "sigmoid_ce":
                per = optax.sigmoid_binary_cross_entropy(
                    logits[..., 0], y.astype(jnp.float32)
                )
                loss = jnp.sum(per * mask) / msum
                acc = jnp.sum(
                    ((logits[..., 0] > 0) == (y > 0)).astype(jnp.float32)
                    * mask
                ) / msum
                return loss, {"loss": loss, "accuracy": acc}
            # mse
            pred = logits if logits.ndim == y.ndim else logits[..., 0]
            per = jnp.mean(
                (pred - y) ** 2, axis=tuple(range(1, pred.ndim))
            ) if pred.ndim > 1 else (pred - y) ** 2
            loss = jnp.sum(per * mask) / msum
            return loss, {"loss": loss}

        return fn

    # -- init / jit -----------------------------------------------------------

    def _init_params(self, x0: jnp.ndarray) -> None:
        rng = jax.random.PRNGKey(self.seed)
        self.params = self.module.init(rng, x0)
        self.opt_state = self.optimizer.init(self.params)

    def _set_accumulation(self, accumulate_steps: int) -> None:
        """(Un)wrap the optimizer in optax.MultiSteps; rebuilds jitted
        fns and re-shapes optimizer state when the setting changes —
        PRESERVING the inner optimizer's moments, so toggling
        accumulation mid-training does not reset Adam's warmup."""
        if accumulate_steps < 1:
            raise ValueError(
                f"accumulate_steps must be >= 1, got {accumulate_steps}"
            )
        current = getattr(self, "_accumulate_steps", 1)
        if accumulate_steps == current:
            return
        base = getattr(self, "_base_optimizer", None)
        if base is None:
            base = self.optimizer
        self._base_optimizer = base
        old_state, was_wrapped = self.opt_state, current > 1
        self.optimizer = base if accumulate_steps == 1 else \
            optax.MultiSteps(base, every_k_schedule=accumulate_steps)
        self._accumulate_steps = accumulate_steps
        self._invalidate_jit()
        if self.params is None:
            return
        if old_state is None:
            # No live moments to carry over (e.g. a restore-best early
            # stop dropped them); fit re-inits for the new optimizer.
            return
        if accumulate_steps == 1:
            # Unwrap: the inner state IS the plain optimizer's state.
            self.opt_state = old_state.inner_opt_state if was_wrapped \
                else old_state
        else:
            new_state = jax.jit(self.optimizer.init)(self.params)
            inner = old_state.inner_opt_state if was_wrapped \
                else old_state
            if inner is not None:
                new_state = new_state._replace(inner_opt_state=inner)
            self.opt_state = new_state

    def _build_step(self, loss_kind: str):
        dtype = jnp.bfloat16 if self.compute_dtype == "bfloat16" else None
        return _cached_program(
            "epoch_fns", self, loss_kind, donate=False,
            builder=lambda: build_epoch_fns(
                self.module,
                self.optimizer,
                self._loss_and_metrics(loss_kind),
                dtype,
            ),
        )

    # -- keras-fit surface ----------------------------------------------------

    def fit(
        self,
        x,
        y,
        epochs: int = 1,
        batch_size: int = 32,
        validation_split: float = 0.0,
        validation_data: tuple | None = None,
        shuffle: bool = True,
        verbose: int = 0,
        callbacks: list | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
        checkpoint_min_interval_s: float = 60.0,
        resume: bool = True,
        accumulate_steps: int = 1,
        quantize_checkpoint: bool = False,
        checkpoint_async: bool = True,
        early_stopping: dict | EarlyStopping | None = None,
        **_,
    ) -> "NeuralEstimator":
        """keras-fit surface plus managed in-loop checkpointing: with
        ``checkpoint_dir`` set, (params, opt_state) persist every
        ``checkpoint_every`` epochs — but at most once per
        ``checkpoint_min_interval_s`` (fast epochs on big models must
        not stall the loop on full-state host transfers; the final
        epoch always saves) — and an interrupted fit resumes from the
        newest checkpoint instead of epoch 0 (``resume=False`` ignores
        existing checkpoints) — the preemption story the reference
        lacks (SURVEY §5.4).

        ``accumulate_steps=N`` accumulates gradients over N batches
        before each optimizer update (``optax.MultiSteps``) — the
        effective batch is N·batch_size without N× the activation
        memory.  When the accumulated batches are all full (dataset a
        multiple of N·batch_size, per-sample masks) the N masked-mean
        grads average to the large-batch mean and trajectories match
        large-batch training to compute-dtype rounding; a padded tail
        batch (or per-token LM masks) weights each batch equally
        rather than by its mask mass.

        Beyond-RAM datasets: when x/y are sharded-dataset views
        (store/sharded.py) the fit STREAMS shards — the whole dataset
        never materializes on host or device (``_fit_streaming``).

        ``quantize_checkpoint=True`` marks the estimator so its SAVED
        artifact stores parameters int8 (ops/quant.py) with optimizer
        state dropped — a ~4-7x smaller serving binary; the live
        in-memory model keeps full precision.

        ``early_stopping`` (an :class:`EarlyStopping` or its REST-JSON
        dict spec, e.g. ``{"monitor": "val_loss", "patience": 3,
        "restoreBestWeights": true}``) stops the loop once the
        monitored metric stalls; any callback may likewise set
        ``model.stop_training = True``."""
        self._quantize_persist = bool(quantize_checkpoint)
        callbacks = build_stop_callbacks(self, callbacks, early_stopping)
        if _is_sharded(x) or _is_sharded(y):
            return self._fit_streaming(
                x, y, epochs=epochs, batch_size=batch_size,
                validation_split=validation_split,
                validation_data=validation_data, shuffle=shuffle,
                verbose=verbose, callbacks=callbacks,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                checkpoint_min_interval_s=checkpoint_min_interval_s,
                resume=resume, accumulate_steps=accumulate_steps,
                checkpoint_async=checkpoint_async,
            )
        self._set_accumulation(accumulate_steps)
        x = np.asarray(as_array(x))
        y_arr = np.asarray(y if not hasattr(y, "to_numpy") else y.to_numpy())
        y_arr = y_arr.reshape(-1) if y_arr.ndim == 2 and y_arr.shape[1] == 1 \
            else y_arr
        loss_kind = self._resolve_loss(y_arr)
        if loss_kind == "softmax_ce":
            y_arr = y_arr.astype(np.int32)
        else:
            y_arr = y_arr.astype(np.float32)

        if validation_data is None and validation_split > 0:
            n_val = int(len(x) * validation_split)
            # Tiny datasets: never let the split empty the train set; skip
            # validation instead of silently training on nothing.
            if 0 < n_val < len(x):
                x, x_val = x[:-n_val], x[-n_val:]
                y_arr, y_val = y_arr[:-n_val], y_arr[-n_val:]
                validation_data = (x_val, y_val)

        if len(x) == 0:
            raise ValueError("cannot batch an empty dataset")
        if self.params is None:
            self._init_params(jnp.asarray(x[:1]))
        elif self.opt_state is None:
            # Quantized (serving) artifacts drop optimizer state;
            # continuation training re-inits moments from zero.
            self.opt_state = jax.jit(self.optimizer.init)(self.params)
        if self._eval_fn is None or self._eval_loss_kind != loss_kind:
            _, self._eval_fn = self._build_step(loss_kind)
            self._eval_loss_kind = loss_kind

        # Upload the dataset once; each epoch is one jitted call that
        # shuffles/batches on device (see build_device_epoch).
        epoch_key = (len(x), batch_size, bool(shuffle), loss_kind)
        if self._device_epoch_key != epoch_key:
            dtype = jnp.bfloat16 if self.compute_dtype == "bfloat16" else None
            self._device_epoch, self._device_epoch_cost = _cached_program(
                "device_epoch", self, loss_kind,
                shapes=(len(x), batch_size, bool(shuffle)),
                builder=lambda: build_device_epoch(
                    self.module,
                    self.optimizer,
                    self._loss_and_metrics(loss_kind),
                    dtype,
                    n=len(x),
                    batch_size=batch_size,
                    shuffle=bool(shuffle),
                ),
                # Shape avatars for the cost probe: the whole-epoch
                # program's flops/HBM, measured once per build.
                cost_args=lambda: (
                    self.params, self.opt_state, x, y_arr,
                    jax.random.PRNGKey(self.seed),
                ),
                want_cost=True,
            )
            self._device_epoch_key = epoch_key
        xs = jnp.asarray(x)
        ys = jnp.asarray(y_arr)
        root_key = jax.random.PRNGKey(self.seed)

        start_epoch = 0
        if checkpoint_dir and resume:
            from learningorchestra_tpu.train import checkpoint as ckpt

            loaded = ckpt.resume_or_none(
                checkpoint_dir,
                {"params": self.params, "opt_state": self.opt_state},
            )
            if loaded is not None:
                state, step, past_history = loaded
                self.params = state["params"]
                self.opt_state = state["opt_state"]
                self.history = TrainHistory(past_history)
                start_epoch = step

        from learningorchestra_tpu.train import checkpoint as ckpt_mod

        params, opt_state = self.params, self.opt_state
        last_save = time.monotonic()
        try:
            for epoch_i in range(start_epoch, epochs):
                if cancel_requested():
                    # Engine-side cancellation (deadline watchdog or
                    # bounded shutdown drain): wind down exactly like
                    # an early stop — params/history stay consistent
                    # at the last completed epoch.
                    self.stop_training = True
                    break
                t0 = time.perf_counter()
                # Chaos probe per epoch: an armed ``preempt`` schedule
                # models the real TPU event — mid-fit, after some
                # checkpoints committed — so the engine-retry →
                # checkpoint-resume path is provable end to end.
                _faults().hit("train.epoch")
                params, opt_state, metrics = self._device_epoch(
                    params, opt_state, xs, ys,
                    jax.random.fold_in(root_key, epoch_i),
                )
                # Re-anchor the estimator each epoch: the epoch call donates
                # its (params, opt_state) arguments, so a raise from a
                # callback/validation below must not strand self.params on
                # deleted buffers.
                self.params, self.opt_state = params, opt_state
                # ONE host transfer for all metric scalars — per-metric
                # float() pays a device round-trip each (remote-TPU
                # dispatch is ~7 ms per call).
                metrics = {
                    k: float(v) for k, v in jax.device_get(metrics).items()
                }
                metrics["epoch_time"] = time.perf_counter() - t0
                # Device-time attribution (obs/costs.py): the metrics
                # device_get above synced the dispatch, so epoch_time
                # IS the device interval; the program's measured flops
                # ride along, giving the per-job ledger (and the MFU
                # gauge) real numerators.  One config check when the
                # costs plane is off.
                _attribute_epoch_cost(self, metrics["epoch_time"])
                if validation_data is not None:
                    vx, vy = validation_data
                    vy = np.asarray(vy)
                    # Only flatten single-column matrices — sequence targets
                    # (B, T) keep their shape (the LM loss path).
                    if vy.ndim == 2 and vy.shape[1] == 1:
                        vy = vy.reshape(-1)
                    vmetrics = self._evaluate_arrays(
                        params, np.asarray(as_array(vx)), vy,
                        batch_size, loss_kind,
                    )
                    metrics.update({f"val_{k}": v for k, v in vmetrics.items()})
                self.history.append(metrics)
                # Trace span per epoch (train step + validation): the
                # job's span tree shows exactly where fit time went —
                # now annotated with the program's measured flops/bytes
                # and achieved-vs-peak utilization, so a trace answers
                # "what was the hardware doing" per epoch.  Single
                # contextvar read when no trace is active.
                obs_tracing.record_span(
                    "epoch", time.perf_counter() - t0, epoch=epoch_i,
                    **_epoch_cost_attrs(self, metrics["epoch_time"]),
                )
                if verbose:
                    _train_logger().info(
                        "epoch %d/%d: %s", epoch_i + 1, epochs, metrics
                    )
                # Callbacks run BEFORE the save decision so an early
                # stop counts as the final epoch under the one shared
                # policy (should_save stopped=...).
                for cb in callbacks or []:
                    if callable(cb):
                        cb(epoch_i, metrics, self)
                if checkpoint_dir and ckpt_mod.should_save(
                            epoch_i, epochs, checkpoint_every,
                            checkpoint_min_interval_s, last_save,
                            stopped=self.stop_training,
                        ):
                    from learningorchestra_tpu.train import checkpoint as ckpt

                    opt_state = self.opt_state
                    if opt_state is None:
                        # restore-best dropped the moments: checkpoint
                        # the restored params with FRESH moments, else
                        # resume=True would replay the last periodic
                        # save's pre-restore params (ADVICE r3).
                        opt_state = jax.jit(self.optimizer.init)(
                            self.params
                        )
                    ckpt.save(
                        checkpoint_dir, epoch_i + 1,
                        {"params": self.params,
                         "opt_state": opt_state},
                        history=dict(self.history),
                        async_save=checkpoint_async,
                    )
                    last_save = time.monotonic()
                if self.stop_training:
                    # A callback (e.g. EarlyStopping) may have replaced
                    # self.params with a restored snapshot — the loop's
                    # own re-anchor above already covered the normal
                    # path, so just stop; do NOT re-assign below.
                    if verbose:
                        _train_logger().info(
                            "early stop after epoch %d", epoch_i + 1
                        )
                    break
        finally:
            if checkpoint_dir:
                # The last async save must be durable when fit returns
                # (and an exception mid-loop must not strand a pending
                # write unpublished for a later fit in this process).
                ckpt_mod.finalize_async(checkpoint_dir)
        return self

    def _fit_streaming(
        self, x, y, *, epochs, batch_size, validation_split,
        validation_data, shuffle, verbose, callbacks, checkpoint_dir,
        checkpoint_every, checkpoint_min_interval_s, resume,
        accumulate_steps, checkpoint_async: bool = True,
    ) -> "NeuralEstimator":
        """Shard-streaming fit over a beyond-host-RAM dataset.

        Contract parity with the in-memory path (same managed
        checkpointing, history, callbacks); mechanics differ where the
        data layout forces it:

        - x/y are views over ONE sharded dataset (x may be the bare
          dataset: it resolves to every column except y's — the
          ``fit(x="$big", y="$big.label")`` request shape);
        - each epoch walks shards in a fresh host-side order; rows
          reshuffle on device WITHIN a shard (store/sharded.py module
          docstring covers the shuffle-granularity trade);
        - shard k+1 loads from disk on an IO thread and starts its
          host→device transfer while the device computes on shard k —
          JAX's async dispatch overlaps them without explicit streams;
        - ``validation_split`` is unsupported (a fractional split of a
          stream would pin an arbitrary shard subset); pass
          ``validation_data`` arrays instead.

        The optimizer step count differs from the in-memory path only
        in batch boundaries at shard edges (each shard's tail batch
        pads, exactly like the in-memory tail).  Reference contract:
        database_api_image/database.py:86-151 (stream-ingest + read-back
        training, the one reference capability round 2 lacked).
        """
        import concurrent.futures

        from learningorchestra_tpu.store import sharded as sh

        if validation_split:
            raise ValueError(
                "validation_split is unsupported for sharded datasets; "
                "pass validation_data=(x, y) arrays"
            )
        if _is_sharded(validation_data):
            raise ValueError(
                "validation_data must be in-memory arrays, not sharded "
                "views (validation sets are small by construction)"
            )
        x, y = sh.resolve_xy_views(x, y)
        # Remember the feature columns so a later predict on the BARE
        # dataset ("x": "$big") selects the same features instead of
        # accidentally feeding the label column too.
        self._sharded_fit_cols = list(x.cols)
        self._set_accumulation(accumulate_steps)

        ds = x.dataset
        y_head = np.asarray(y.head(256))
        loss_kind = self._resolve_loss(y_head)
        y_cast = np.int32 if loss_kind == "softmax_ce" else np.float32
        x_head = np.asarray(x.head(1), np.float32)
        if self.params is None:
            self._init_params(jnp.asarray(x_head))
        elif self.opt_state is None:
            # Quantized (serving) artifacts drop optimizer state.
            self.opt_state = jax.jit(self.optimizer.init)(self.params)
        if self._eval_fn is None or self._eval_loss_kind != loss_kind:
            _, self._eval_fn = self._build_step(loss_kind)
            self._eval_loss_kind = loss_kind

        dtype = jnp.bfloat16 if self.compute_dtype == "bfloat16" else None
        loss_fn = self._loss_and_metrics(loss_kind)
        epoch_fns: dict[int, Any] = {}

        def fn_for(rows: int):
            # One compilation per distinct shard length — all full
            # shards share one executable; the tail adds a second.
            # Resolved through the process-wide cache so a re-submitted
            # streaming job (same dataset, same shard layout) skips
            # every trace.
            if rows not in epoch_fns:
                epoch_fns[rows] = _cached_program(
                    "device_epoch", self, loss_kind,
                    shapes=(rows, min(batch_size, rows), bool(shuffle)),
                    builder=lambda: build_device_epoch(
                        self.module, self.optimizer, loss_fn, dtype,
                        n=rows, batch_size=min(batch_size, rows),
                        shuffle=bool(shuffle),
                    ),
                )
            return epoch_fns[rows]

        def load(k: int):
            # IO thread: disk → host arrays → START the async H2D copy.
            # Dtypes pass through exactly as the in-memory path's
            # as_array does (int features stay int — token models).
            xs = x.load_shard(k)
            ys = y.load_shard(k).astype(y_cast)
            return jax.device_put(xs), jax.device_put(ys)

        start_epoch = 0
        if checkpoint_dir and resume:
            from learningorchestra_tpu.train import checkpoint as ckpt

            loaded = ckpt.resume_or_none(
                checkpoint_dir,
                {"params": self.params, "opt_state": self.opt_state},
            )
            if loaded is not None:
                state, step, past_history = loaded
                self.params = state["params"]
                self.opt_state = state["opt_state"]
                self.history = TrainHistory(past_history)
                start_epoch = step

        from learningorchestra_tpu.train import checkpoint as ckpt_mod

        params, opt_state = self.params, self.opt_state
        root_key = jax.random.PRNGKey(self.seed)
        last_save = time.monotonic()
        try:
            with concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="shard-io"
            ) as io:
                for epoch_i in range(start_epoch, epochs):
                    if cancel_requested():
                        # Same contract as the in-memory loop.
                        self.stop_training = True
                        break
                    t0 = time.perf_counter()
                    _faults().hit("train.epoch")  # see in-memory loop
                    # Seeded per (seed, epoch), NOT once per fit: a
                    # checkpoint-resumed epoch 6 must walk the same shard
                    # order the uninterrupted run would have (and the
                    # distributed path already does — one convention).
                    order = (
                        np.random.default_rng(
                            [self.seed, 3, epoch_i]
                        ).permutation(ds.n_shards) if shuffle
                        else np.arange(ds.n_shards)
                    )
                    acc = sh.WeightedMetrics()
                    nxt = io.submit(load, int(order[0]))
                    for pos, k in enumerate(order):
                        xs, ys = nxt.result()
                        if pos + 1 < len(order):
                            nxt = io.submit(load, int(order[pos + 1]))
                        rows = ds.shard_rows[int(k)]
                        params, opt_state, metrics = fn_for(rows)(
                            params, opt_state, xs, ys,
                            jax.random.fold_in(
                                root_key, epoch_i * ds.n_shards + pos
                            ),
                        )
                        # Re-anchor every shard: the epoch fn donates its
                        # state, so an interrupt must not strand
                        # self.params on deleted buffers.
                        self.params, self.opt_state = params, opt_state
                        acc.add(jax.device_get(metrics), rows)
                    metrics = acc.result()
                    metrics["epoch_time"] = time.perf_counter() - t0
                    if validation_data is not None:
                        vx, vy = validation_data
                        vy = np.asarray(vy)
                        if vy.ndim == 2 and vy.shape[1] == 1:
                            vy = vy.reshape(-1)
                        vmetrics = self._evaluate_arrays(
                            params, np.asarray(as_array(vx)), vy,
                            batch_size, loss_kind,
                        )
                        metrics.update(
                            {f"val_{k2}": v for k2, v in vmetrics.items()}
                        )
                    self.history.append(metrics)
                    obs_tracing.record_span(
                        "epoch", time.perf_counter() - t0,
                        epoch=epoch_i, streaming=True,
                    )
                    if verbose:
                        _train_logger().info(
                            "epoch %d/%d: %s", epoch_i + 1, epochs, metrics
                        )
                    for cb in callbacks or []:
                        if callable(cb):
                            cb(epoch_i, metrics, self)
                    if checkpoint_dir and ckpt_mod.should_save(
                                epoch_i, epochs, checkpoint_every,
                                checkpoint_min_interval_s, last_save,
                                stopped=self.stop_training,
                            ):
                        from learningorchestra_tpu.train import (
                            checkpoint as ckpt,
                        )

                        opt_state = self.opt_state
                        if opt_state is None:
                            # restore-best: fresh moments for the
                            # restored params (see in-memory loop).
                            opt_state = jax.jit(self.optimizer.init)(
                                self.params
                            )
                        ckpt.save(
                            checkpoint_dir, epoch_i + 1,
                            {"params": self.params,
                             "opt_state": opt_state},
                            history=dict(self.history),
                            async_save=checkpoint_async,
                        )
                        last_save = time.monotonic()
                    if self.stop_training:
                        # Per-shard re-anchor above already synced
                        # self.params; a callback may have replaced it
                        # (restore-best), so don't re-assign below.
                        if verbose:
                            _train_logger().info(
                                "early stop after epoch %d", epoch_i + 1
                            )
                        break
        finally:
            if checkpoint_dir:
                # Same durability contract as the in-memory
                # loop, incl. the exception path.
                ckpt_mod.finalize_async(checkpoint_dir)
        return self

    def _evaluate_arrays(self, params, x, y, batch_size, loss_kind):
        if loss_kind == "softmax_ce":
            y = y.astype(np.int32)
        else:
            y = y.astype(np.float32)
        xb, yb, mb = _batch_data(x, y, batch_size, _NoShuffle())
        metrics = self._eval_fn(
            params, jnp.asarray(xb), jnp.asarray(yb), jnp.asarray(mb)
        )
        return {k: float(v) for k, v in metrics.items()}

    def evaluate(self, x, y, batch_size: int = 128, **_) -> dict:
        if _is_sharded(x) or _is_sharded(y):
            return self._evaluate_streaming(x, y, batch_size)
        x = np.asarray(as_array(x))
        y = np.asarray(y if not hasattr(y, "to_numpy") else y.to_numpy())
        # Only flatten a single-column matrix; multi-output regression
        # targets (n, k>1) must keep their shape.
        if y.ndim == 2 and y.shape[1] == 1:
            y = y.reshape(-1)
        loss_kind = self._resolve_loss(y)
        if self._eval_fn is None or self._eval_loss_kind != loss_kind:
            if self.params is None:
                raise RuntimeError("evaluate() before fit()")
            self._step_fn, self._eval_fn = self._build_step(loss_kind)
            self._eval_loss_kind = loss_kind
        return self._evaluate_arrays(
            self.params, x, y, batch_size, loss_kind
        )

    def _evaluate_streaming(self, x, y, batch_size: int) -> dict:
        """Shard-streaming evaluate (same x/y resolution as
        ``_fit_streaming``); metrics are row-weighted across shards,
        perplexity averaged in log domain (exp-after-mean)."""
        from learningorchestra_tpu.store import sharded as sh

        x, y = sh.resolve_xy_views(x, y)
        if self.params is None:
            raise RuntimeError("evaluate() before fit()")
        loss_kind = self._resolve_loss(np.asarray(y.head(256)))
        if self._eval_fn is None or self._eval_loss_kind != loss_kind:
            self._step_fn, self._eval_fn = self._build_step(loss_kind)
            self._eval_loss_kind = loss_kind
        ds = x.dataset
        acc = sh.WeightedMetrics()
        for k in range(ds.n_shards):
            acc.add(
                self._evaluate_arrays(
                    self.params, x.load_shard(k), y.load_shard(k),
                    batch_size, loss_kind,
                ),
                ds.shard_rows[k],
            )
        return acc.result()

    def predict(self, x, batch_size: int = 512, **_):
        if _is_sharded(x):
            # Stream shards; the OUTPUT still materializes (n_rows,
            # out_dim) on host — logits/classes are orders of magnitude
            # smaller than beyond-RAM features, but callers with huge
            # row counts should predict per shard view themselves.
            from learningorchestra_tpu.store import sharded as sh

            if isinstance(x, sh.ShardedDataset):
                # Bare dataset: prefer the columns the streaming fit
                # trained on (they exclude the label); otherwise all.
                cols = getattr(self, "_sharded_fit_cols", None)
                if cols and all(c in x.fields for c in cols):
                    # Always the LIST form: a one-element list keeps
                    # the (rows, 1) matrix shape fit trained on
                    # (ShardedView collapses only tensor columns).
                    view = x.view(cols)
                else:
                    view = x.view(x.fields)
            else:
                view = x
            # Dtype passes through untouched — int token columns must
            # stay int for embedding lookups, same as the fit loader.
            return np.concatenate([
                self.predict(view.load_shard(k), batch_size)
                for k in range(view.dataset.n_shards)
            ], axis=0)
        from learningorchestra_tpu.serve.bucketing import (
            bucket_for,
            pad_rows,
        )

        x = np.asarray(as_array(x))
        outs = []
        for i in range(0, len(x), batch_size):
            xb = x[i:i + batch_size]
            k = xb.shape[0]
            # The ragged final slice used to dispatch at its own shape,
            # so EVERY distinct tail length re-traced and re-compiled
            # apply.  Pad it up to its power-of-two bucket (capped at
            # batch_size — full batches dispatch at batch_size exactly)
            # and slice the pad rows off the output: compile count is
            # bounded by the bucket set, never by tail diversity.  Same
            # helper and discipline as the serving path (serve/).
            bucket = bucket_for(k, batch_size)
            padded = jnp.asarray(
                pad_rows(xb, bucket) if k != bucket else xb
            )
            out = np.asarray(
                self._apply_for(bucket, example=padded)(
                    self.params, padded,
                )
            )
            outs.append(out[:k] if k != bucket else out)
        return np.concatenate(outs, axis=0)

    def _apply_for(self, rows: int, example=None):
        """Cache-resolved jitted ``apply`` for a ``rows``-row input.

        Keyed through :func:`compile_cache.apply_program_key` —
        optimizer/loss play no part in inference, and ``rows`` is the
        shape-bucket dimension, so every predict job AND the serving
        path share one executable per (architecture, bucket) and the
        cache's miss counter counts buckets, not calls.  ``example``
        (a bucket-shaped input) lets a first build run the cost probe
        — the same ProgramCost the serving path attributes against."""
        fns = getattr(self, "_apply_fns", None)
        if fns is None:
            fns = self._apply_fns = {}
        fn = fns.get(rows)
        if fn is None:
            from learningorchestra_tpu.train import compile_cache as cc

            key = cc.apply_program_key(self.module, rows=rows)
            label = f"apply:{type(self.module).__name__}:b{rows}"

            def builder():
                jitted = jax.jit(self.module.apply)
                if example is not None and self.params is not None:
                    _probe_program_cost(
                        key, label, jitted,
                        lambda: (self.params, example),
                    )
                return jitted

            fn = fns[rows] = cc.get_cache().get_or_build(
                key, builder, label=label
            )
        return fn

    def predict_classes(self, x, batch_size: int = 512):
        return np.argmax(self.predict(x, batch_size), axis=-1)

    def score(self, x, y) -> float:
        return float(self.evaluate(x, y).get("accuracy", 0.0))

    # -- persistence (pytree checkpoint; see store/volumes.py) ---------------

    def state_dict(self, *, quantize: bool = False) -> dict:
        """``quantize=True`` stores large parameter tensors int8
        (ops/quant.py row-wise format, ~4x smaller) and DROPS the
        optimizer state — a quantized artifact is a serving/inference
        binary; continuation training re-inits moments."""
        extras = {
            "history": dict(self.history),
            "accumulate_steps": getattr(self, "_accumulate_steps", 1),
            # Feature-column memory for bare-sharded-dataset predict;
            # must survive persistence or the restored model reverts
            # to feeding the label column.
            "sharded_fit_cols": getattr(
                self, "_sharded_fit_cols", None
            ),
        }
        if quantize:
            from learningorchestra_tpu.ops.quant import quantize_pytree

            return {
                "params": quantize_pytree(jax.device_get(self.params)),
                "opt_state": None,
                **extras,
            }
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            **extras,
        }

    def load_state_dict(self, state: dict) -> None:
        from learningorchestra_tpu.ops.layers import (
            has_separate_qkv,
            migrate_separate_qkv,
        )
        from learningorchestra_tpu.ops.quant import (
            dequantize_pytree,
            has_quantized_leaves,
        )

        params = state["params"]
        if params is not None and has_quantized_leaves(params):
            params = dequantize_pytree(params)
        if params is not None and has_separate_qkv(params):
            # Legacy separate-projection artifact meeting the fused
            # default: block-stack into the qkv layout (bit-identical
            # outputs).  fused_qkv=False models keep their layout by
            # initializing params before loading.
            if self.params is None or not has_separate_qkv(self.params):
                params = migrate_separate_qkv(params)
        self.params = params
        # Restore the accumulation wrapper FIRST so the optimizer and
        # the restored opt_state structure agree (a MultiSteps state
        # under a plain optimizer crashes deep inside the jitted scan).
        self._set_accumulation(state.get("accumulate_steps", 1))
        self.opt_state = state["opt_state"]
        self.history = TrainHistory(state.get("history", {}))
        cols = state.get("sharded_fit_cols")
        if cols:
            self._sharded_fit_cols = list(cols)

    def __getstate__(self):
        """dill support: drop jitted closures, keep module + host arrays.

        With ``self._quantize_persist`` set (the train request's
        ``quantize_checkpoint``), large parameter tensors persist int8
        and the optimizer state is dropped — the artifact path's
        quantized binary format."""
        d = dict(self.__dict__)
        d.pop("_decode_fns", None)  # jitted decode scans (GreedyDecodeMixin)
        d["_step_fn"] = None
        d["_eval_fn"] = None
        d["_apply_fn"] = None
        d.pop("_apply_fns", None)  # per-bucket jitted applies
        d["_device_epoch"] = None
        d["_device_epoch_key"] = None
        d["_device_epoch_cost"] = None
        d["params"] = jax.device_get(d["params"]) if d["params"] is not None \
            else None
        d["opt_state"] = jax.device_get(d["opt_state"]) \
            if d["opt_state"] is not None else None
        if d.get("_quantize_persist") and d["params"] is not None:
            from learningorchestra_tpu.ops.quant import quantize_pytree

            d["params"] = quantize_pytree(d["params"])
            d["opt_state"] = None
        return d

    def __setstate__(self, state):
        from learningorchestra_tpu.ops.quant import (
            dequantize_pytree,
            has_quantized_leaves,
        )

        if state.get("params") is not None and has_quantized_leaves(
            state["params"]
        ):
            state = dict(state)
            state["params"] = dequantize_pytree(state["params"])
        # No qkv migration here: a dill'd instance carries its OWN
        # module (with its fused_qkv setting), so its params always
        # match — only load_state_dict crosses layout versions.
        self.__dict__.update(state)


class _NoShuffle:
    """Identity 'rng' for deterministic batching."""

    def permutation(self, n: int):
        return np.arange(n)
