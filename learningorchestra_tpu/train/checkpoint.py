"""Managed in-loop training checkpoints (orbax-backed, shard-aware).

The reference has NO intra-training checkpointing: a mid-job failure
loses the job and distributed training returns weights only at the end
(reference: training_function/train_function.py:84-87; README.md:193-197
documents that a task running when the cluster dies "is lost").  On TPU,
preemption is routine, so the train executor checkpoints the estimator
state every N epochs and PATCH re-runs resume instead of restarting —
closing the gap SURVEY §5.4 calls out.

Sharding contract:
- ``save`` takes the state tree AS IS — sharded ``jax.Array`` leaves are
  written by orbax shard-by-shard from the process(es) that own them;
  there is **no host gather** (a v4-32 ResNet/BERT state never
  materializes on one host).
- ``load_latest`` restores INTO the template's placement: a template of
  mesh-sharded arrays yields sharded arrays on that mesh (which may be a
  *different* mesh shape than the one that saved — orbax reshards on
  read); a host-numpy template yields numpy.
- Multi-process: ``save``/``load_latest`` are collective — every process
  calls them; only process 0 writes the ``latest.json`` marker and
  prunes old steps.

Layout under ``<dir>``::

    step_<n>/        orbax pytree checkpoint (params + opt_state)
    latest.json      {"step": n, "history": {...}} — atomically replaced

``latest.json`` is written AFTER the step directory commits, so a crash
mid-save leaves the previous checkpoint intact and discoverable.
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path

from learningorchestra_tpu.concurrency_rt import make_lock

KEEP = 2  # retained checkpoints; older ones are pruned after each save


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _is_primary() -> bool:
    import jax

    return jax.process_index() == 0


def _barrier(tag: str) -> None:
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _publish(directory: Path, step: int, history: dict | None) -> None:
    """Commit point: the ``latest.json`` marker names the newest FULLY
    WRITTEN checkpoint; readers never see a step the data hasn't
    landed for.  Also prunes old steps."""
    marker = {"step": step, "history": history or {}}
    tmp = directory / "latest.json.tmp"
    tmp.write_text(json.dumps(marker))
    os.replace(tmp, directory / "latest.json")
    for old in sorted(directory.glob("step_*")):
        try:
            n = int(old.name.split("_", 1)[1])
        except ValueError:
            continue
        if n <= step - KEEP:
            shutil.rmtree(old, ignore_errors=True)


# Async bookkeeping is PER CHECKPOINT DIRECTORY: the job engine runs
# fits concurrently on worker threads (jobs/engine.py, max_workers=8),
# so a single global slot would let one job's finalize swallow (or
# republish over) another's marker.  Each directory gets its own
# AsyncCheckpointer + one-pending-save slot, guarded by its own lock.


class _AsyncSlot:
    def __init__(self):
        self.lock = make_lock("_AsyncSlot.lock")
        self.ckpt = None
        self.pending = None  # (step, history) awaiting publish


_SLOTS: dict[str, _AsyncSlot] = {}
_SLOTS_LOCK = make_lock("checkpoint._SLOTS_LOCK")
_ATEXIT = {"registered": False}


def _slot(directory: Path) -> _AsyncSlot:
    key = str(directory)
    with _SLOTS_LOCK:
        if key not in _SLOTS:
            _SLOTS[key] = _AsyncSlot()
            if not _ATEXIT["registered"]:
                import atexit

                # A process must never exit with a written-but-
                # unpublished checkpoint (the marker is the commit
                # point).
                atexit.register(finalize_async)
                _ATEXIT["registered"] = True
        return _SLOTS[key]


def _finalize_slot(key: str, slot: _AsyncSlot) -> None:
    with slot.lock:
        if slot.pending is None:
            return
        step, history = slot.pending
        slot.pending = None
        slot.ckpt.wait_until_finished()
        _publish(Path(key), step, history)


def finalize_async(directory: str | Path | None = None) -> None:
    """Block until in-flight async saves commit and publish their
    markers — for one checkpoint directory, or (``None``) all of them.
    Fit loops call this at loop exit so the last checkpoint is durable
    when fit() returns — the same guarantee the sync path gives per
    save."""
    if directory is not None:
        key = str(Path(directory))
        with _SLOTS_LOCK:
            slot = _SLOTS.get(key)
        if slot is not None:
            _finalize_slot(key, slot)
        return
    with _SLOTS_LOCK:
        items = list(_SLOTS.items())
    for key, slot in items:
        _finalize_slot(key, slot)


def save(directory: str | Path, step: int, state: dict,
         history: dict | None = None, *,
         async_save: bool = False) -> Path:
    """Persist {params, opt_state} at ``step``; returns the step path.

    Collective under multi-process JAX; sharded leaves are written
    without gathering to host.

    ``async_save=True`` (single-process only) returns as soon as the
    device arrays are snapshotted: serialization runs on a background
    thread while training continues — on a remote-TPU link the
    device→host transfer dominates save time, so overlapping it buys
    a whole checkpoint's wall-clock per save.  The marker publishes at
    the NEXT save or at :func:`finalize_async`, so a crash mid-write
    resumes from the previous durable step (the same fallback a crash
    mid-sync-save has).
    """
    import jax

    directory = Path(directory)
    if async_save and jax.process_count() == 1:
        import orbax.checkpoint as ocp

        slot = _slot(directory)
        with slot.lock:
            # Previous save to THIS directory commits + publishes
            # first (one in flight per directory).
            if slot.pending is not None:
                p_step, p_history = slot.pending
                slot.pending = None
                slot.ckpt.wait_until_finished()
                _publish(directory, p_step, p_history)
            if slot.ckpt is None:
                slot.ckpt = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler()
                )
            directory.mkdir(parents=True, exist_ok=True)
            path = directory / f"step_{step}"
            if path.exists():
                shutil.rmtree(path)
            slot.ckpt.save(path, args=ocp.args.StandardSave(state))
            slot.pending = (step, history)
        return path
    # Sync path: flush any pending ASYNC save to this directory first —
    # otherwise a stale pending marker could later publish OVER this
    # save's marker and rewind latest.json to an older step.
    finalize_async(directory)
    if _is_primary():
        directory.mkdir(parents=True, exist_ok=True)
        path = directory / f"step_{step}"
        if path.exists():
            shutil.rmtree(path)
    path = directory / f"step_{step}"
    _barrier(f"ckpt-pre-{step}")
    with _checkpointer() as ck:
        ck.save(path, state)
    # StandardCheckpointer.save commits (atomic rename) before returning,
    # on every process, so the marker write below cannot race the data.
    if _is_primary():
        _publish(directory, step, history)
    _barrier(f"ckpt-post-{step}")
    return path


def load_latest(directory: str | Path, template: dict):
    """Restore the newest checkpoint as (state, step, history), or None.

    ``template`` is a concrete pytree with the target structure (e.g. a
    freshly-initialized {params, opt_state}) — orbax uses it to rebuild
    optax's namedtuple states exactly, and restores each leaf onto the
    template leaf's placement: numpy template → numpy out; mesh-sharded
    ``jax.Array`` template → sharded arrays on that mesh (any mesh
    shape — restore-time resharding is how a job resumes on a different
    slice than the one that saved).
    """
    directory = Path(directory)
    # Flush any in-flight async save first: a reader in this process
    # must see the newest step, not the marker from one save ago.
    finalize_async(directory)
    marker_path = directory / "latest.json"
    if not marker_path.exists():
        return None
    try:
        marker = json.loads(marker_path.read_text())
        step = int(marker["step"])
    except (ValueError, KeyError, json.JSONDecodeError):
        return None
    path = directory / f"step_{step}"
    if not path.exists():
        return None
    with _checkpointer() as ck:
        state = ck.restore(path, template)
    return state, step, marker.get("history") or {}


def load_step(directory: str | Path, step: int, template: dict):
    """Restore one SPECIFIC step (template-placed, like
    ``load_latest``), or None when that step directory is absent.  The
    MPMD fit surface uses this to pull every stage partition back to
    the newest COMMON step — a crash between partition saves must not
    resume stages from different epochs."""
    directory = Path(directory)
    finalize_async(directory)
    path = directory / f"step_{step}"
    if not path.exists():
        return None
    with _checkpointer() as ck:
        return ck.restore(path, template)


def publish_marker(directory: str | Path, step: int,
                   history: dict | None = None) -> None:
    """Public commit-point writer for fit surfaces that persist state
    in their OWN sub-layout (MPMD writes one orbax directory per
    pipeline stage under ``<dir>/<part>/``): the same atomic
    ``latest.json`` the single-directory path writes, at the top
    level, AFTER every partition has committed — so the journal's
    marker wait and a resuming fit see only whole checkpoints.  The
    prune pass inside ``_publish`` globs ``step_*`` at this level,
    which a partitioned layout doesn't create."""
    directory = Path(directory)
    if _is_primary():
        directory.mkdir(parents=True, exist_ok=True)
        _publish(directory, step, history)
    _barrier(f"ckpt-marker-{step}")


def resume_or_none(directory, template: dict):
    """``load_latest`` with configuration-mismatch errors translated to
    an actionable message — the shared resume front door for every fit
    surface (NeuralEstimator, PipelinedTransformer, DistributedTrainer
    uses load_latest directly with a mesh template)."""
    try:
        return load_latest(directory, template)
    except (ValueError, TypeError) as exc:
        raise ValueError(
            "checkpoint resume failed: the saved state does not match "
            "the current configuration (model, optimizer, or "
            "accumulate_steps changed since the checkpoint was "
            "written). Re-run with resume=False or the original "
            "settings."
        ) from exc


def should_save(epoch_i: int, epochs: int, every: int,
                min_interval_s: float, last_save: float,
                *, stopped: bool = False) -> bool:
    """One save policy for every fit loop: periodic saves every
    ``every`` epochs (``every <= 0`` disables checkpointing entirely —
    including the final/stop saves below) throttled to one per
    ``min_interval_s`` (fast epochs on big models must not stall the
    loop on full-state transfers); the FINAL epoch always saves when
    checkpointing is enabled, and ``stopped=True`` (an early-stop
    callback ended training) counts as final."""
    import time as _time

    if every <= 0:
        return False
    return (
        epoch_i + 1 == epochs
        or stopped
        or (
            (epoch_i + 1) % every == 0
            and _time.monotonic() - last_save >= min_interval_s
        )
    )
