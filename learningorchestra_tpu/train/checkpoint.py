"""Managed in-loop training checkpoints (orbax-backed).

The reference has NO intra-training checkpointing: a mid-job failure
loses the job and distributed training returns weights only at the end
(reference: training_function/train_function.py:84-87; README.md:193-197
documents that a task running when the cluster dies "is lost").  On TPU,
preemption is routine, so the train executor checkpoints the estimator
state every N epochs and PATCH re-runs resume instead of restarting —
closing the gap SURVEY §5.4 calls out.

Layout under ``<dir>``::

    step_<n>/        orbax pytree checkpoint (params + opt_state)
    latest.json      {"step": n, "history": {...}} — atomically replaced

``latest.json`` is written AFTER the step directory commits, so a crash
mid-save leaves the previous checkpoint intact and discoverable.
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path

KEEP = 2  # retained checkpoints; older ones are pruned after each save


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save(directory: str | Path, step: int, state: dict,
         history: dict | None = None) -> Path:
    """Persist {params, opt_state} at ``step``; returns the step path."""
    import jax

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    path = directory / f"step_{step}"
    if path.exists():
        shutil.rmtree(path)
    with _checkpointer() as ck:
        ck.save(path, jax.device_get(state))
    marker = {"step": step, "history": history or {}}
    tmp = directory / "latest.json.tmp"
    tmp.write_text(json.dumps(marker))
    os.replace(tmp, directory / "latest.json")
    for old in sorted(directory.glob("step_*")):
        try:
            n = int(old.name.split("_", 1)[1])
        except ValueError:
            continue
        if n <= step - KEEP:
            shutil.rmtree(old, ignore_errors=True)
    return path


def load_latest(directory: str | Path, template: dict):
    """Restore the newest checkpoint as (state, step, history), or None.

    ``template`` is a concrete pytree with the target structure (e.g. a
    freshly-initialized {params, opt_state}) — orbax uses it to rebuild
    optax's namedtuple states exactly.
    """
    directory = Path(directory)
    marker_path = directory / "latest.json"
    if not marker_path.exists():
        return None
    try:
        marker = json.loads(marker_path.read_text())
        step = int(marker["step"])
    except (ValueError, KeyError, json.JSONDecodeError):
        return None
    path = directory / f"step_{step}"
    if not path.exists():
        return None
    import jax

    with _checkpointer() as ck:
        state = ck.restore(path, jax.device_get(template))
    return state, step, marker.get("history") or {}
