"""Durable AOT executable store — the compiled hot set survives the
process.

The process-wide ``CompiledProgramCache`` (train/compile_cache.py)
amortizes tracing across jobs, but it dies with the process: a restart,
deploy or failover re-pays XLA tracing for the entire hot set at
exactly the moment a production fleet can least afford it (ROADMAP
item 3).  The persistent XLA cache only dedups the *XLA compile* step —
Python tracing and executable loading still cost seconds per program on
TPU.

This store closes the gap with JAX's AOT export: when the deep cost
probe (obs/costs.py) lowers-and-compiles a just-built program, the
serialized executable payload (``jax.experimental.serialize_executable``
— a picklable ``(blob, in_tree, out_tree)`` tuple) is *offered* here and
written next to the XLA disk cache.  A later process loads it with
``deserialize_and_load`` and installs the restored ``Compiled`` straight
into the program cache — first dispatch skips trace AND compile.

Blob format (one file per program, ``<fingerprint>.aotx``)::

    LOAOT1\\n
    {json header: version, key, label, deviceSig, sha256, bytes}\\n
    <pickled serialize_executable payload>

Safety contract: a stale or corrupt blob must degrade to a live
re-trace, never a crash — every load validates magic, format version,
key, device signature (compiled executables pin device handles;
``train/compile_cache.py::_device_signature``) and a payload checksum;
any mismatch counts ``loadErrors``, deletes the blob and returns None.
The fault points ``cache.aot_load`` / ``cache.aot_store`` (faults/
plane.py) chaos-test exactly this degradation.

A ``manifest.json`` beside the blobs records the hot set (fingerprint,
label, hit count, measured bytes) ordered by observed heat — the boot
pre-warm (services/context.py) walks it hottest-first.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Any

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.log import get_logger, kv

__all__ = [
    "AOTExecutableStore",
    "enabled",
    "get_store",
    "reset_store",
    "stats_snapshot",
]

logger = get_logger("aot_store")

_MAGIC = b"LOAOT1\n"
_FORMAT_VERSION = 1
_MANIFEST = "manifest.json"


def _faults():
    """Lazy fault-plane handle (the compile-cache idiom): this module
    sits on the train import path and must stay flat."""
    from learningorchestra_tpu import faults

    return faults


def _device_signature() -> tuple:
    from learningorchestra_tpu.train import compile_cache

    return compile_cache._device_signature()


class AOTExecutableStore:
    """On-disk store of AOT-serialized executables + hot-set manifest.

    All mutation happens under one lock; blob and manifest writes are
    atomic (tmp + rename) so a crash mid-store leaves the previous
    state, never a torn file.  Loading is deliberately paranoid — see
    the module docstring's safety contract.
    """

    def __init__(
        self,
        root: str,
        *,
        max_entries: int = 64,
        max_bytes: int = 1 << 30,
    ):
        self.root = os.path.expanduser(root)
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self._lock = make_lock("AOTExecutableStore._lock")
        # key -> {"label", "hits", "bytes", "storedAt"}
        self._manifest: dict[str, dict] = {}
        # Counters (process lifetime; stats() snapshots them).
        self.hits = 0
        self.misses = 0
        self.load_errors = 0
        self.stores = 0
        self.store_errors = 0
        self.evictions = 0
        self.call_fallbacks = 0
        os.makedirs(self.root, exist_ok=True)
        self._read_manifest()

    # -- paths / persistence -------------------------------------------------

    def _blob_path(self, key: str) -> str:
        # Keys are sha256 hexdigests (compile_cache.fingerprint), safe
        # as filenames verbatim.
        return os.path.join(self.root, f"{key}.aotx")

    def _read_manifest(self) -> None:
        path = os.path.join(self.root, _MANIFEST)
        try:
            with open(path, encoding="utf-8") as fh:
                raw = json.load(fh)
            entries = raw.get("entries", {})
            if isinstance(entries, dict):
                self._manifest = {
                    str(k): dict(v) for k, v in entries.items()
                    if isinstance(v, dict)
                }
        except FileNotFoundError:
            return
        except Exception as exc:  # noqa: BLE001 — a torn manifest
            # must not fail boot; the blobs re-register as they are
            # re-offered.
            logger.warning(kv(
                event="aot_manifest_unreadable", path=path,
                error=repr(exc),
            ))
            self._manifest = {}

    def _write_manifest_locked(self) -> None:
        path = os.path.join(self.root, _MANIFEST)
        tmp = f"{path}.tmp.{os.getpid()}"
        doc = {"version": _FORMAT_VERSION, "entries": self._manifest}
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
            os.replace(tmp, path)
        except OSError as exc:
            logger.warning(kv(
                event="aot_manifest_write_failed", error=repr(exc),
            ))
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _drop_locked(self, key: str, *, evicted: bool = False) -> None:
        self._manifest.pop(key, None)
        if evicted:
            self.evictions += 1
        try:
            os.unlink(self._blob_path(key))
        except OSError:
            pass

    def _prune_locked(self, keep: str | None = None) -> None:
        """Bound the store to max_entries/max_bytes, evicting the
        coldest (fewest hits, oldest) blobs first.  ``keep`` — the key
        just stored — is never evicted (the compile cache's
        never-evict-the-just-inserted rule)."""
        def total() -> int:
            return sum(
                int(rec.get("bytes", 0) or 0)
                for rec in self._manifest.values()
            )

        while self._manifest and (
            len(self._manifest) > self.max_entries
            or total() > self.max_bytes
        ):
            victims = sorted(
                (k for k in self._manifest if k != keep),
                key=lambda k: (
                    int(self._manifest[k].get("hits", 0) or 0),
                    float(self._manifest[k].get("storedAt", 0.0) or 0.0),
                ),
            )
            if not victims:
                break
            self._drop_locked(victims[0], evicted=True)

    # -- store / load --------------------------------------------------------

    def offer(self, key: str, payload: Any, *,
              label: str | None = None) -> bool:
        """Persist one program's serialized-executable ``payload`` (the
        tuple ``serialize_executable.serialize`` returned).  Best
        effort: any failure counts ``storeErrors`` and the build it
        rides proceeds untouched.  Re-offering a stored key refreshes
        its label/bytes and bumps its heat."""
        try:
            _faults().hit("cache.aot_store")
            blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
            header = {
                "version": _FORMAT_VERSION,
                "key": key,
                "label": label,
                "deviceSig": [list(d) for d in _device_signature()],
                "sha256": hashlib.sha256(blob).hexdigest(),
                "bytes": len(blob),
            }
            path = self._blob_path(key)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(_MAGIC)
                fh.write(json.dumps(header).encode("utf-8"))
                fh.write(b"\n")
                fh.write(blob)
            os.replace(tmp, path)
        except Exception as exc:  # noqa: BLE001 — never fail the build
            with self._lock:
                self.store_errors += 1
            logger.warning(kv(
                event="aot_store_failed", key=key[:12],
                label=label or "", error=repr(exc),
            ))
            return False
        with self._lock:
            rec = self._manifest.get(key)
            if rec is None:
                rec = self._manifest[key] = {"hits": 0}
            rec["label"] = label
            rec["bytes"] = len(blob)
            rec["storedAt"] = time.time()
            rec["hits"] = int(rec.get("hits", 0) or 0) + 1
            self.stores += 1
            self._prune_locked(keep=key)
            self._write_manifest_locked()
        return True

    def load(self, key: str):
        """Deserialize-and-load the stored executable for ``key``;
        ``None`` on a miss OR any validation/decode failure (the
        caller falls back to a live re-trace — a bad blob must never
        fail a request).  Corrupt blobs are deleted so the error pays
        once."""
        with self._lock:
            known = key in self._manifest
        path = self._blob_path(key)
        try:
            _faults().hit("cache.aot_load")
            with open(path, "rb") as fh:
                if fh.read(len(_MAGIC)) != _MAGIC:
                    raise ValueError("bad magic")
                header = json.loads(fh.readline().decode("utf-8"))
                blob = fh.read()
            if header.get("version") != _FORMAT_VERSION:
                raise ValueError(
                    f"format version {header.get('version')!r} != "
                    f"{_FORMAT_VERSION}"
                )
            if header.get("key") != key:
                raise ValueError("header key mismatch")
            sig = [list(d) for d in _device_signature()]
            if header.get("deviceSig") != sig:
                raise ValueError("device signature mismatch")
            if hashlib.sha256(blob).hexdigest() != header.get("sha256"):
                raise ValueError("payload checksum mismatch")
            from jax.experimental import serialize_executable

            parts = pickle.loads(blob)
            if not isinstance(parts, tuple):
                parts = (parts,)
            compiled = serialize_executable.deserialize_and_load(*parts)
        except FileNotFoundError:
            with self._lock:
                self.misses += 1
                if known:
                    # Blob vanished under the manifest (operator rm,
                    # partial copy): forget it.
                    self._manifest.pop(key, None)
                    self._write_manifest_locked()
            return None
        except BaseException as exc:
            from learningorchestra_tpu.jobs.engine import Preempted

            if isinstance(exc, Preempted):
                # The fault plane's preempt mode models device-level
                # preemption — that is the JOB retry loop's contract,
                # not a blob-corruption fallback.
                raise
            injected = type(exc).__name__ == "FaultInjected"
            with self._lock:
                self.load_errors += 1
                if not injected:
                    # Real corruption/mismatch: pay the error once.
                    # An INJECTED error is transient chaos — deleting
                    # a healthy blob would turn a drill into data loss.
                    self._drop_locked(key)
                    self._write_manifest_locked()
            logger.warning(kv(
                event="aot_load_failed", key=key[:12],
                error=repr(exc),
            ))
            return None
        with self._lock:
            self.hits += 1
            rec = self._manifest.get(key)
            if rec is None:
                # Blob present without a manifest row (torn manifest
                # at a previous crash): re-register it.
                rec = self._manifest[key] = {
                    "label": header.get("label"),
                    "bytes": len(blob),
                    "storedAt": time.time(),
                    "hits": 0,
                }
            rec["hits"] = int(rec.get("hits", 0) or 0) + 1
            self._write_manifest_locked()
        return compiled

    def note_call_fallback(self) -> None:
        """A restored executable failed at CALL time and its consumer
        re-traced live (train/compile_cache.py guard)."""
        with self._lock:
            self.call_fallbacks += 1

    # -- introspection -------------------------------------------------------

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._manifest

    def entry(self, key: str) -> dict | None:
        with self._lock:
            rec = self._manifest.get(key)
            return dict(rec) if rec is not None else None

    def manifest_entries(self) -> list[dict]:
        """Hot set, hottest first — the boot pre-warm's work list."""
        with self._lock:
            entries = [
                {"key": key, **rec} for key, rec in self._manifest.items()
            ]
        entries.sort(
            key=lambda rec: int(rec.get("hits", 0) or 0), reverse=True
        )
        return entries

    def stats(self) -> dict:
        with self._lock:
            persisted_bytes = sum(
                int(rec.get("bytes", 0) or 0)
                for rec in self._manifest.values()
            )
            return {
                "enabled": True,
                "dir": self.root,
                "persistedEntries": len(self._manifest),
                "persistedBytes": persisted_bytes,
                "maxEntries": self.max_entries,
                "maxBytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "loadErrors": self.load_errors,
                "stores": self.stores,
                "storeErrors": self.store_errors,
                "evictions": self.evictions,
                "callFallbacks": self.call_fallbacks,
                "entries_detail": [
                    {
                        "key": key[:12],
                        "label": rec.get("label"),
                        "hits": int(rec.get("hits", 0) or 0),
                        "bytes": int(rec.get("bytes", 0) or 0),
                    }
                    for key, rec in self._manifest.items()
                ],
            }


# -- process-wide singleton ---------------------------------------------------

_store: AOTExecutableStore | None = None
_store_lock = make_lock("aot_store._store_lock")


def _cfg():
    from learningorchestra_tpu.config import get_config

    return get_config().aot


def enabled() -> bool:
    """Off by default (LO_TPU_AOT_ENABLED): restored executables pin
    exact shapes/dtypes and cross-run state, so durability is an
    explicit deployment opt-in — the deploy manifests enable it."""
    try:
        cfg = _cfg()
    except Exception:  # noqa: BLE001 — a config error must not turn
        return False  # every compile-cache miss into a crash
    return bool(cfg.enabled) and cfg.max_entries > 0


def get_store() -> AOTExecutableStore | None:
    """The process-wide store, or None when disabled.  An explicitly
    installed store (``reset_store`` with overrides — tests) is served
    regardless of config."""
    global _store
    with _store_lock:
        if _store is not None:
            return _store
    if not enabled():
        return None
    with _store_lock:
        if _store is None:
            cfg = _cfg()
            try:
                _store = AOTExecutableStore(
                    cfg.dir,
                    max_entries=cfg.max_entries,
                    max_bytes=cfg.max_bytes,
                )
            except OSError as exc:
                logger.warning(kv(
                    event="aot_store_unavailable", dir=cfg.dir,
                    error=repr(exc),
                ))
                return None
        return _store


def reset_store(**overrides) -> AOTExecutableStore | None:
    """Replace the singleton (tests; config swap).  With ``overrides``
    (root/max_entries/max_bytes) builds an explicit store regardless of
    config; bare call drops it for lazy rebuild from config."""
    global _store
    with _store_lock:
        if overrides:
            _store = AOTExecutableStore(**overrides)
            return _store
        _store = None
    return get_store()


def stats_snapshot() -> dict:
    """Stats for the monitoring payload and Prometheus exposition —
    zeros when disabled, so scrape shape stays stable."""
    store = get_store()
    if store is None:
        return {
            "enabled": False,
            "persistedEntries": 0,
            "persistedBytes": 0,
            "hits": 0,
            "misses": 0,
            "loadErrors": 0,
            "stores": 0,
            "storeErrors": 0,
            "evictions": 0,
            "callFallbacks": 0,
        }
    return store.stats()
