"""DecodeStream — one generation request's lifecycle in the engine.

A stream is the unit the continuous-batching scheduler admits: one
prompt, one KV slot (while live), one bounded event queue the transport
drains.  The SSE writer in ``api/server.py`` duck-types the payload on
``sse_events()`` and calls :meth:`abort` when the client disconnects
mid-body — the PR-14 :class:`~learningorchestra_tpu.jobs.cancel.
CancelToken` carries that request into the decode worker, which frees
the stream's KV pages and slot at the next step boundary.

Non-stream requests ride the same object (``eager=False``): the engine
skips the per-step device sync for them (jax's async dispatch pipelines
the whole decode like the solo ``lax.scan`` does) and the HTTP thread
blocks on :meth:`wait_done`.
"""

from __future__ import annotations

import queue
import threading
import time
import uuid

from learningorchestra_tpu.jobs.cancel import CancelToken

#: Event-queue bound: ``total`` tokens plus lifecycle events always
#: fit, but a reader that stopped draining must not grow memory.
_QUEUE_CAP = 4096


class DecodeStream:
    """One prompt's decode: identity, cancel token, event queue."""

    __slots__ = (
        "stream_id", "model", "prompt", "t0", "total", "eager",
        "token", "events", "arrived", "first_at", "last_at",
        "tokens", "error", "_done",
    )

    def __init__(self, model: str, prompt, t0: int, total: int,
                 *, eager: bool):
        self.stream_id = uuid.uuid4().hex[:12]
        self.model = model
        self.prompt = prompt  # int32 (t0,) host array
        self.t0 = int(t0)
        self.total = int(total)
        # eager: the transport wants every token as it lands (SSE), so
        # the worker syncs the step's token column to host each step.
        # Lazy streams let dispatch run ahead; tokens surface at done.
        self.eager = bool(eager)
        self.token = CancelToken()
        self.events: queue.Queue = queue.Queue(maxsize=_QUEUE_CAP)
        self.arrived = time.perf_counter()
        self.first_at: float | None = None
        self.last_at: float | None = None
        self.tokens: list[int] = []  # emitted continuation tokens
        self.error: str | None = None
        self._done = threading.Event()

    # -- engine side ---------------------------------------------------------

    def _push(self, name: str, doc: dict) -> None:
        try:
            self.events.put_nowait((name, doc))
        except queue.Full:
            pass  # reader stopped draining; terminal state still lands
        # via _done / token, which the transports consult.

    def push_token(self, tok: int, pos: int) -> None:
        self.tokens.append(tok)
        self._push("token", {"t": tok, "i": pos})

    def finish(self) -> None:
        self._push("done", self.summary())
        self._done.set()

    def fail(self, message: str) -> None:
        self.error = message
        self.token.cancel(message)
        self._push("error", {"stream": self.stream_id, "error": message})
        self._done.set()

    def mark_aborted(self) -> None:
        """Worker-side acknowledgement that the slot was freed after
        :meth:`abort` — terminal for both transports."""
        self._push("aborted", {
            "stream": self.stream_id,
            "reason": self.token.reason or "aborted",
        })
        self._done.set()

    # -- transport side ------------------------------------------------------

    def abort(self, reason: str = "aborted") -> None:
        """Request teardown (client disconnect / DELETE).  The decode
        worker observes the token at its next step boundary and frees
        the slot + KV pages; idempotent like the token itself."""
        self.token.cancel(reason)

    def done(self) -> bool:
        return self._done.is_set()

    def wait_done(self, timeout: float) -> bool:
        return self._done.wait(timeout)

    def summary(self) -> dict:
        doc = {
            "stream": self.stream_id,
            "model": self.model,
            "promptTokens": self.t0,
            "newTokens": len(self.tokens),
            "tokens": list(self.tokens),
        }
        if self.first_at is not None:
            doc["ttftMs"] = round(
                (self.first_at - self.arrived) * 1e3, 3
            )
        if self.error is not None:
            doc["error"] = self.error
        return doc

    def sse_events(self):
        """The transport's event iterator: ``(event-name, doc)`` pairs,
        ending with a terminal ``done``/``error``/``aborted``.  Polls
        the cancel token between queue waits so an engine that died
        without a terminal event still ends the response."""
        yield "open", {
            "stream": self.stream_id,
            "model": self.model,
            "promptTokens": self.t0,
            "maxTotal": self.total,
        }
        while True:
            try:
                name, doc = self.events.get(timeout=0.25)
            except queue.Empty:
                if self.token.cancelled():
                    yield "aborted", {
                        "stream": self.stream_id,
                        "reason": self.token.reason or "aborted",
                    }
                    return
                if self._done.is_set() and self.events.empty():
                    return  # terminal event already drained
                continue
            yield name, doc
            if name in ("done", "error", "aborted"):
                return
