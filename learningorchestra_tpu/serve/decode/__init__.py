"""Resident streaming-decode engine (continuous batching + SSE).

``DecodeEngine`` is the serving service's facade; ``DecodeStream`` is
the per-request lifecycle object the API layer's SSE writer drains;
``pages``/``build_step`` hold the KV page pools and the bucketed step
executables.
"""

from learningorchestra_tpu.serve.decode.engine import DecodeEngine
from learningorchestra_tpu.serve.decode.pages import PagePool, build_step
from learningorchestra_tpu.serve.decode.streams import DecodeStream

__all__ = ["DecodeEngine", "DecodeStream", "PagePool", "build_step"]
