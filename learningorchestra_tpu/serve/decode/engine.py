"""DecodeEngine — resident continuous-batching LM serving.

The serve/ path was one-shot ``apply`` only; this engine opens the
streaming-generation workload (ROADMAP item 1): per-model decode
workers step KV page pools (``pages.py``) with one jitted step per
(arch, slot-bucket, kv-bucket), admit newly-arrived prompts into
in-flight steps (continuous batching — no barrier batching), emit
tokens over SSE, and tear a stream down cooperatively through its
PR-14 CancelToken at the next step boundary.

Fleet integration: when a model has a live replica set, each new
stream is routed to a replica by the set's P2C router over live decode
slot counts, and every step's device time lands in the per-model
attributed device-time ledger — the same signal the autoscaler's
``LO_TPU_FLEET_UP_DEVICE_FRAC`` threshold reads.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from learningorchestra_tpu.concurrency_rt import make_condition, make_lock
from learningorchestra_tpu.log import get_logger, kv
from learningorchestra_tpu.obs import flight as obs_flight
from learningorchestra_tpu.obs.metrics import get_registry
from learningorchestra_tpu.serve.batcher import QueueFull
from learningorchestra_tpu.serve.bucketing import bucket_for
from learningorchestra_tpu.serve.decode.pages import PagePool, build_step
from learningorchestra_tpu.serve.decode.streams import DecodeStream
from learningorchestra_tpu.serve.registry import ServeError

logger = get_logger("decode")

#: Ceiling on a non-stream request's wait for its streams to finish.
_NONSTREAM_TIMEOUT_S = 300.0

#: Lazy (non-SSE) pools sync to host every this-many steps so async
#: dispatch cannot run unboundedly ahead of the device.
_SYNC_STRIDE = 32


class _DecodeHists:
    """Identity-cached handles on the decode metric families — the
    ``_PredictHist`` rebind idiom (serve/service.py): a
    ``reset_registry()`` mid-life re-homes the series, the steady
    state pays one identity check.  TTFT and inter-token latency are
    the two decode SLO primitives; the token counter feeds throughput
    rollups."""

    __slots__ = ("_reg", "_ttft", "_itl", "_tokens", "_bound")

    def __init__(self):
        self._reg = None
        self._ttft = None
        self._itl = None
        self._tokens = None
        self._bound: dict = {}

    def _bind(self, model: str):
        reg = get_registry()
        if reg is not self._reg:
            self._ttft = reg.histogram(
                "lo_serving_decode_ttft_seconds",
                "Time to first generated token per streamed decode "
                "(admission wait + prefill steps + first step).",
                labels=("model",),
            )
            self._itl = reg.histogram(
                "lo_serving_decode_itl_seconds",
                "Inter-token latency between consecutive streamed "
                "decode tokens.",
                labels=("model",),
            )
            self._tokens = reg.counter(
                "lo_serving_decode_tokens_total",
                "Generated tokens per served model (all transports).",
                labels=("model",),
            )
            self._bound = {}
            self._reg = reg
        bound = self._bound.get(model)
        if bound is None:
            if len(self._bound) >= 256:
                self._bound.clear()
            bound = self._bound[model] = (
                self._ttft.bind(model=model),
                self._itl.bind(model=model),
            )
        return bound

    def ttft(self, dt_s: float, model: str) -> None:
        self._bind(model)[0].observe(dt_s)

    def itl(self, dt_s: float, model: str) -> None:
        self._bind(model)[1].observe(dt_s)

    def tokens(self, n: int, model: str) -> None:
        self._bind(model)
        self._tokens.inc(n, model=model)


_decode_hists = _DecodeHists()


class _ModelDecoder:
    """One model's decode worker: admission queue, page pools, step
    loop.  All pool state is owned by the worker thread; the condition
    variable hands streams in and wakes the worker for aborts."""

    def __init__(self, engine: "DecodeEngine", name: str):
        self.engine = engine
        self.name = name
        self.cfg = engine.cfg
        self._cv = make_condition("_ModelDecoder._cv")
        self._pending: deque = deque()
        self._pools: dict = {}  # (replica_idx | None, kv) → PagePool
        self._streams: dict = {}  # stream_id → DecodeStream (active)
        self._step_state: dict = {}  # (S, kv) → (step fn, cache shapes)
        self._thread: threading.Thread | None = None
        self._closed = False
        self.steps = 0

    # -- submission (any thread) --------------------------------------------

    def submit(self, stream: DecodeStream) -> None:
        with self._cv:
            if self._closed:
                raise ServeError(
                    f"decode for {self.name!r} is shut down"
                )
            active = len(self._streams) + len(self._pending)
            if active >= self.cfg.max_streams:
                obs_flight.record(
                    "decode", "queue_full",
                    model=self.name, stream=stream.stream_id,
                    active=active,
                )
                raise QueueFull(
                    f"decode for {self.name!r} at max_streams="
                    f"{self.cfg.max_streams}"
                )
            obs_flight.record(
                "decode", "submit",
                model=self.name, stream=stream.stream_id,
                total=stream.total,
            )
            self._pending.append(stream)
            self._streams[stream.stream_id] = stream
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=f"decode-{self.name}",
                    daemon=True,
                )
                self._thread.start()
            self._cv.notify_all()

    def abort(self, stream_id: str, reason: str) -> bool:
        with self._cv:
            stream = self._streams.get(stream_id)
            if stream is None:
                return False
            stream.token.cancel(reason)
            obs_flight.record(
                "decode", "abort",
                model=self.name, stream=stream_id, reason=reason,
            )
            self._cv.notify_all()
            return True

    def wake(self) -> None:
        with self._cv:
            self._cv.notify_all()

    # -- worker --------------------------------------------------------------

    def _any_live(self) -> bool:
        return any(p.live for p in self._pools.values())

    def _run(self) -> None:
        idle_since: float | None = None
        while True:
            with self._cv:
                while (not self._closed and not self._pending
                       and not self._any_live()):
                    if idle_since is None:
                        idle_since = time.monotonic()
                    waited = time.monotonic() - idle_since
                    if waited >= self.cfg.idle_timeout_s:
                        # Idle past the knob: free the resident pools
                        # (KV HBM back to the allocator) and park; the
                        # next submit restarts the worker.
                        self._pools.clear()
                        self._step_state.clear()
                        self._thread = None
                        return
                    self._cv.wait(
                        timeout=self.cfg.idle_timeout_s - waited
                    )
                if self._closed:
                    pending = list(self._pending)
                    self._pending.clear()
                    pools = list(self._pools.values())
                    self._pools.clear()
                    self._thread = None
                    break
                idle_since = None
                pending = list(self._pending)
                self._pending.clear()
            deferred = []
            for stream in pending:
                try:
                    admitted = self._admit(stream)
                except Exception as exc:  # noqa: BLE001 — a bug in
                    # admission (or its error handler) costs ONE
                    # stream, never the model's worker thread: an
                    # unfinished stream here would stall every
                    # in-flight SSE client on a no_timeout route.
                    logger.error("decode admit raised %s", kv(
                        model=self.name, stream=stream.stream_id,
                        error=str(exc),
                    ))
                    self._finish(
                        stream, error=f"admission failed: {exc}"
                    )
                    continue
                if not admitted:
                    deferred.append(stream)
            self._step_all()
            if deferred:
                with self._cv:
                    # Back to the FRONT: arrival order is admission
                    # order once capacity frees up.
                    self._pending.extendleft(reversed(deferred))
        # closed: fail whatever never got (or was mid) service.
        for stream in pending:
            stream.fail("decode engine shut down")
        for pool in pools:
            for slot, stream in enumerate(pool.streams):
                if stream is not None:
                    pool.release(slot)
                    stream.fail("decode engine shut down")
        with self._cv:
            self._streams.clear()

    # -- admission -----------------------------------------------------------

    def _route_replica(self):
        """P2C-pick a replica for a new stream when the model is
        fleet-served; None keeps the registry-resident single path.
        Depth signal = live decode slots per replica, the decode
        analogue of the predict router's queue depth."""
        try:
            rs = self.engine.service.fleet.registered_set(self.name)
        except Exception:  # noqa: BLE001 — routing must not kill admit
            rs = None
        if rs is None:
            return None
        with rs._lock:
            replicas = list(rs._replicas)
        if not replicas:
            return None
        depths = []
        for replica in replicas:
            depths.append(sum(
                pool.live for key, pool in self._pools.items()
                if key[0] == replica.idx
            ))
        order = rs.router.choose(depths)
        return replicas[order[0]]

    def _admit(self, stream: DecodeStream) -> bool:
        if stream.token.cancelled():
            self._finish(stream, aborted=True)
            return True
        try:
            replica = self._route_replica()
            ridx = None if replica is None else replica.idx
            kvlen = bucket_for(
                stream.total,
                min(self.cfg.max_kv, self._max_len()),
            )
            pool = self._pools.get((ridx, kvlen))
            if pool is None:
                pool = self._pools[(ridx, kvlen)] = PagePool(
                    kvlen, self.cfg.max_slots, replica_idx=ridx,
                )
                obs_flight.record(
                    "decode", "pool_grow",
                    model=self.name, kv=kvlen,
                    slots=self.cfg.max_slots,
                    replica=-1 if ridx is None else ridx,
                )
            slot = pool.admit(
                stream,
                lambda want: self._step_for(want, kvlen)[1],
            )
        except Exception as exc:  # noqa: BLE001 — fail THIS stream
            logger.error("decode admit failed %s", kv(
                model=self.name, stream=stream.stream_id,
                error=str(exc),
            ))
            obs_flight.record(
                "decode", "admit_failed",
                model=self.name, stream=stream.stream_id,
                error=str(exc),
            )
            self._finish(stream, error=f"admission failed: {exc}")
            return True
        if slot is not None:
            obs_flight.record(
                "decode", "admit",
                model=self.name, stream=stream.stream_id,
                kv=kvlen, slot=slot,
            )
        return slot is not None

    def _max_len(self) -> int:
        entry = self.engine.service.registry.get(self.name)
        return int(getattr(entry.estimator, "max_len", self.cfg.max_kv))

    # -- stepping ------------------------------------------------------------

    def _step_for(self, nslots: int, kvlen: int):
        """(jitted step, cache shapes) for one (S, Tk) cell, resolved
        through the cross-job compile cache: fingerprints, hit/miss
        stats, warm-start hints and AOT eligibility — never a private
        dict of executables.  Memoized on the decoder (dies with the
        model teardown) and recorded on the registry entry's
        ``decode_warm`` so replica pre-warm can replay it."""
        state = self._step_state.get((nslots, kvlen))
        if state is None:
            from learningorchestra_tpu.train import compile_cache as cc

            entry = self.engine.service.registry.get(self.name)
            module = entry.estimator.module
            key = cc.program_key(
                "decode_step",
                module=cc.module_fingerprint(module),
                optimizer=None,
                loss="-",
                dtype="-",
                shapes=("decode_step", nslots, kvlen),
            )
            label = (
                f"decode:{type(module).__name__}"
                f":s{nslots}:k{kvlen}"
            )
            state = cc.get_cache().get_or_build(
                key, lambda: build_step(module, nslots, kvlen),
                label=label,
            )
            self._step_state[(nslots, kvlen)] = state
            entry.decode_warm[(nslots, kvlen)] = True
        return state

    def _params_for(self, pool: PagePool):
        entry = self.engine.service.registry.get(self.name)
        if pool.replica_idx is None:
            return entry.params
        try:
            rs = self.engine.service.fleet.registered_set(self.name)
            if rs is not None:
                with rs._lock:
                    replicas = list(rs._replicas)
                for replica in replicas:
                    if replica.idx == pool.replica_idx:
                        params, _ = replica.place(
                            entry, np.zeros((1, 1), np.int32)
                        )
                        return params
        except Exception:  # noqa: BLE001 — scaled-down replica →
            pass  # degrade to registry-resident params
        return entry.params

    def _step_all(self) -> None:
        from learningorchestra_tpu import faults

        for key in list(self._pools):
            pool = self._pools[key]
            # Abort sweep FIRST: a cancelled stream's pages are freed
            # within one step boundary of the cancel, even if the
            # step itself then faults.
            for slot, stream in enumerate(pool.streams):
                if stream is not None and stream.token.cancelled():
                    pool.release(slot)
                    self._finish(stream, aborted=True)
            if not pool.live:
                continue
            try:
                faults.hit("serve.decode_step")
                self._step_pool(pool)
            except Exception as exc:  # noqa: BLE001 — chaos/device
                # Blast radius = this pool's in-flight streams (the
                # real scope of a device fault mid-step); the worker
                # and the other pools stay healthy.
                logger.error("decode step failed %s", kv(
                    model=self.name, pool=f"{key}", error=str(exc),
                ))
                obs_flight.record(
                    "decode", "step_error",
                    model=self.name, pool=f"{key}", error=str(exc),
                )
                for slot, stream in enumerate(pool.streams):
                    if stream is not None:
                        pool.release(slot)
                        self._finish(
                            stream, error=f"decode step failed: {exc}"
                        )

    def _step_pool(self, pool: PagePool) -> None:
        import jax.numpy as jnp

        from learningorchestra_tpu.obs import costs as obs_costs

        step, _ = self._step_for(pool.nslots, pool.kv)
        live = np.array(
            [s is not None for s in pool.streams], bool
        )
        t0s = np.array(
            [s.t0 if s is not None else pool.kv + 1
             for s in pool.streams],
            np.int32,
        )
        eager = any(
            s is not None and s.eager for s in pool.streams
        )
        # ``pool.pos`` is host state mutated in place right after this
        # dispatch; jax's CPU backend may alias numpy buffers
        # zero-copy, so a lazily-executed step would read positions
        # from the FUTURE once the host loop runs ahead of the device
        # (e.g. behind a bucket-grow compile).  Snapshot per dispatch —
        # ``t0s``/``live`` above are already fresh per-call arrays.
        pos_now = pool.pos.copy()
        t_start = time.perf_counter()
        pool.cache, pool.buf, col = step(
            self._params_for(pool), pool.cache, pool.buf,
            jnp.asarray(pos_now), jnp.asarray(t0s),
            jnp.asarray(live),
        )
        pool.steps += 1
        self.steps += 1
        col_host = None
        if eager or pool.steps % _SYNC_STRIDE == 0:
            # SSE wants the token NOW; lazy pools sync on a stride so
            # async dispatch pipelines the loop like the solo scan.
            col_host = np.asarray(col)
        now = time.perf_counter()
        synced = col_host is not None
        for slot, stream in enumerate(pool.streams):
            if stream is None:
                continue
            nxt_pos = int(pool.pos[slot]) + 1
            pool.pos[slot] = nxt_pos
            if nxt_pos >= stream.t0 and col_host is not None \
                    and stream.eager:
                self._emit(stream, int(col_host[slot]), nxt_pos, now)
            if nxt_pos >= stream.total - 1:
                # Terminal: the full row (prompt + continuation) is in
                # the buffer; lazy streams surface everything here.
                row = np.asarray(pool.buf[slot])
                synced = True
                if not stream.eager:
                    stream.tokens = [
                        int(t) for t in row[stream.t0: stream.total]
                    ]
                    stream.first_at = stream.first_at or now
                    _decode_hists.ttft(
                        stream.first_at - stream.arrived, self.name
                    )
                    obs_flight.record(
                        "decode", "ttft",
                        model=self.name, stream=stream.stream_id,
                        ttftS=round(
                            stream.first_at - stream.arrived, 4
                        ),
                    )
                    _decode_hists.tokens(
                        len(stream.tokens), self.name
                    )
                pool.release(slot)
                self._finish(stream, row=row)
        # Devtime attribution flushes at every host sync, whichever
        # transport forced it — eager token read (per step), lazy
        # stride boundary, or a terminal row read — so non-stream
        # decode feeds the autoscaler's LO_TPU_FLEET_UP_DEVICE_FRAC
        # signal too.  Between syncs the async backlog's device work
        # is paid inside the syncing call, so measuring to HERE (past
        # the row reads above) captures the stride's full cost as one
        # amortized sample.
        pool.pending_devtime += time.perf_counter() - t_start
        if synced and obs_costs.enabled():
            led = obs_costs.devtime()
            weight = led.will_record(self.name)
            if weight:
                led.record_model(
                    weight, pool.pending_devtime, None, None,
                    self.name, f"dec{pool.nslots}x{pool.kv}",
                )
            pool.pending_devtime = 0.0

    def _emit(self, stream: DecodeStream, tok: int, pos: int,
              now: float) -> None:
        if stream.first_at is None:
            stream.first_at = now
            _decode_hists.ttft(now - stream.arrived, self.name)
            obs_flight.record(
                "decode", "ttft",
                model=self.name, stream=stream.stream_id,
                ttftS=round(now - stream.arrived, 4),
            )
        else:
            _decode_hists.itl(now - stream.last_at, self.name)
        stream.last_at = now
        stream.push_token(tok, pos)
        _decode_hists.tokens(1, self.name)

    def _finish(self, stream: DecodeStream, *, row=None,
                error: str | None = None,
                aborted: bool = False) -> None:
        if error is not None:
            stream.fail(error)
        elif aborted:
            stream.mark_aborted()
        else:
            stream.finish()
        with self._cv:
            self._streams.pop(stream.stream_id, None)

    # -- lifecycle / observability -------------------------------------------

    def warm_replica(self, replica, entry) -> None:
        """Run one dummy step per recorded (S, Tk) cell against the
        replica's placed params — pays the per-device executable
        load/compile before the router may pick the replica (the
        decode leg of PR-16 replica pre-warm)."""
        import jax.numpy as jnp

        for (nslots, kvlen) in sorted(entry.decode_warm):
            step, cache_shapes = self._step_for(nslots, kvlen)
            pool = PagePool(kvlen, nslots, replica_idx=replica.idx)
            pool._alloc(cache_shapes, nslots)
            params, _ = replica.place(
                entry, np.zeros((1, 1), np.int32)
            )
            step(
                params, pool.cache, pool.buf,
                jnp.zeros(nslots, jnp.int32),
                jnp.full(nslots, kvlen + 1, jnp.int32),
                jnp.zeros(nslots, bool),
            )

    def stats(self) -> dict:
        with self._cv:
            pending = len(self._pending)
            active = len(self._streams)
            # Snapshot under the cv: the worker clears/inserts pool
            # entries concurrently (idle parking, admission).
            pools_snap = list(self._pools.values())
        pools = [
            {
                "kv": pool.kv,
                "slots": pool.nslots,
                "live": pool.live,
                "steps": pool.steps,
                "pageBytes": pool.page_bytes(),
                "replica": pool.replica_idx,
            }
            for pool in pools_snap
        ]
        return {
            "activeStreams": active,
            "pending": pending,
            "steps": self.steps,
            "pools": pools,
        }

    def close(self) -> None:
        with self._cv:
            self._closed = True
            thread = self._thread
            self._cv.notify_all()
        if thread is not None and thread.is_alive():
            thread.join(timeout=10.0)
        with self._cv:
            pending = list(self._pending)
            self._pending.clear()
            self._streams.clear()
            pools = list(self._pools.values())
            self._pools.clear()
            self._step_state.clear()
        for stream in pending:
            stream.fail("decode engine shut down")
        for pool in pools:
            for slot, stream in enumerate(pool.streams):
                if stream is not None:
                    pool.release(slot)
                    stream.fail("decode engine shut down")


class DecodeEngine:
    """Facade the serving service owns: per-model decoders, request
    validation, the stream/non-stream transports."""

    def __init__(self, service):
        self.service = service
        self.cfg = service.ctx.config.decode
        self._lock = make_lock("DecodeEngine._lock")
        self._decoders: dict[str, _ModelDecoder] = {}
        self._closed = False

    # -- request surface -----------------------------------------------------

    def _decoder_for(self, name: str) -> _ModelDecoder:
        with self._lock:
            if self._closed:
                raise ServeError("decode engine is shut down")
            decoder = self._decoders.get(name)
            if decoder is None:
                decoder = self._decoders[name] = _ModelDecoder(
                    self, name
                )
            return decoder

    @staticmethod
    def _as_prompt_rows(prompts) -> list[np.ndarray]:
        """Request JSON → per-stream prompt rows.  Rows may be RAGGED
        (each stream carries its own t0 — continuous batching decodes
        them independently); pad id 0 is reserved."""
        if isinstance(prompts, np.ndarray):
            prompts = prompts.tolist()
        if not isinstance(prompts, (list, tuple)) or not prompts:
            raise ServeError("'prompts' must be a non-empty array")
        if not isinstance(prompts[0], (list, tuple, np.ndarray)):
            prompts = [prompts]
        rows = []
        for row in prompts:
            try:
                r = np.asarray(row, dtype=np.int32)
            except (ValueError, TypeError) as exc:
                raise ServeError(
                    f"prompt row is not an int array: {exc}"
                ) from None
            if r.ndim != 1 or r.shape[0] == 0:
                raise ServeError(
                    "each prompt must be a non-empty 1-D token array"
                )
            if (r == 0).any():
                raise ServeError(
                    "prompts must not contain pad id 0"
                )
            rows.append(r)
        return rows

    def _open_stream(self, name: str, decoder: _ModelDecoder,
                     prompt: np.ndarray, max_new: int, max_len: int,
                     *, eager: bool) -> DecodeStream:
        t0 = int(prompt.shape[0])
        cap = min(max_len, self.cfg.max_kv)
        if t0 >= cap:
            raise ServeError(
                f"prompt length {t0} exceeds decode capacity {cap} "
                f"(model max_len / LO_TPU_DECODE_MAX_KV)"
            )
        max_new = max(1, min(int(max_new), self.cfg.max_new_tokens))
        total = min(cap, t0 + max_new)
        stream = DecodeStream(name, prompt, t0, total, eager=eager)
        decoder.submit(stream)
        return stream

    def generate(self, name: str, prompts, *,
                 max_new_tokens: int = 32, stream: bool = False,
                 temperature=None, top_k=None, top_p=None,
                 seed: int = 0):
        """Entry point behind ``POST /serve/<model>/generate``.

        Greedy decodes run on the resident engine (stream or not);
        sampling parameters fall back to the solo jitted scan
        (non-stream only — a sampled decode has no per-step identity
        to stream against the engine's greedy executables)."""
        entry = self.service.registry.get(name)
        estimator = entry.estimator
        if not hasattr(estimator, "generate"):
            raise ServeError(
                f"artifact {name!r} ({type(estimator).__name__}) is "
                "not a generative LM; only GreedyDecodeMixin models "
                "can serve /generate"
            )
        sampling = (
            temperature is not None or top_k is not None
            or top_p is not None
        )
        rows = self._as_prompt_rows(prompts)
        if sampling or not self.cfg.enabled:
            if stream:
                raise ServeError(
                    "streaming decode requires the resident engine "
                    "(greedy only, LO_TPU_DECODE_ENABLED=1); drop the "
                    "sampling parameters or set stream=false"
                )
            return self._solo_generate(
                name, entry, rows, max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed,
            )
        if stream and len(rows) != 1:
            raise ServeError(
                "stream=true serves exactly one prompt per request"
            )
        decoder = self._decoder_for(name)
        max_len = int(getattr(estimator, "max_len", self.cfg.max_kv))
        streams = [
            self._open_stream(
                name, decoder, row, max_new_tokens, max_len,
                eager=stream,
            )
            for row in rows
        ]
        entry.requests += 1
        if stream:
            return streams[0]
        t0 = time.perf_counter()
        for s in streams:
            remaining = _NONSTREAM_TIMEOUT_S - (
                time.perf_counter() - t0
            )
            if not s.wait_done(max(0.1, remaining)):
                for other in streams:
                    other.abort("decode timed out")
                raise ServeError("decode timed out")
        failed = [s for s in streams if s.error is not None]
        if failed:
            raise ServeError(failed[0].error)
        aborted = [
            s for s in streams
            if s.token.cancelled() and s.error is None
        ]
        if aborted:
            raise ServeError(
                f"decode aborted: {aborted[0].token.reason}"
            )
        return {
            "model": name,
            "tokens": [
                s.prompt.tolist() + s.tokens for s in streams
            ],
            "newTokens": [s.tokens for s in streams],
            "streams": [s.summary() for s in streams],
        }

    def _solo_generate(self, name, entry, rows, max_new_tokens, *,
                       temperature, top_k, top_p, seed):
        """Per-shape solo scan fallback (sampling / engine disabled):
        one call per distinct prompt length so ragged rows stay legal."""
        out_tokens: list[list[int]] = []
        for row in rows:
            try:
                buf = entry.estimator.generate(
                    row[None, :], max_new_tokens=int(max_new_tokens),
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    seed=int(seed),
                )
            except ValueError as exc:
                # Bad sampling spec (top_k without temperature, ...)
                # is a client error, not a server fault → 406.
                raise ServeError(str(exc)) from None
            out_tokens.append(np.asarray(buf)[0].tolist())
        entry.requests += 1
        return {
            "model": name,
            "tokens": out_tokens,
            "newTokens": [
                t[rows[i].shape[0]:] for i, t in enumerate(out_tokens)
            ],
            "sampled": temperature is not None,
        }

    def abort(self, name: str, stream_id: str,
              reason: str = "aborted by client") -> bool:
        with self._lock:
            decoder = self._decoders.get(name)
        if decoder is None:
            return False
        return decoder.abort(stream_id, reason)

    # -- fleet / lifecycle ---------------------------------------------------

    def warm_replica(self, name: str, replica) -> None:
        """Decode leg of replica pre-warm: replay every recorded
        (slot-bucket, kv-bucket) step against the new replica's
        placed params.  Failures are the caller's to log — a replica
        that can't warm still serves cold."""
        entry = self.service.registry.peek(name)
        if entry is None or not entry.decode_warm:
            return
        self._decoder_for(name).warm_replica(replica, entry)

    def drop_model(self, name: str) -> None:
        with self._lock:
            decoder = self._decoders.pop(name, None)
        if decoder is not None:
            decoder.close()

    def stats(self) -> dict:
        with self._lock:
            decoders = dict(self._decoders)
        return {
            "enabled": bool(self.cfg.enabled),
            "models": {
                name: d.stats() for name, d in decoders.items()
            },
        }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            decoders = list(self._decoders.values())
            self._decoders.clear()
        for decoder in decoders:
            decoder.close()
