"""KV page pools — resident decode state, bucketed on both axes.

One pool per (model, routed replica, KV-length bucket): a batch of S
decode *slots* over a KV cache of Tk *pages* per slot.  Both S and Tk
are power-of-two buckets (``serve/bucketing.py`` discipline), so a
whole deployment runs at most ``log2(max_slots)+1`` ×
``log2(max_kv)+1`` step executables per architecture — the small fixed
hot set the pjit serving papers converge on — and every one resolves
through the cross-job compile cache
(:mod:`~learningorchestra_tpu.train.compile_cache`), so fingerprints,
hit/miss stats and AOT eligibility all apply.

The continuous-batching trick is the per-row ``cache_index``: the
attention decode branch (ops/layers.py) accepts a (S,)-shaped index,
so slots sit at DIFFERENT sequence positions inside one jitted step —
a newly admitted prompt starts its one-token-per-step prefill in the
same dispatch that extends its neighbours.  Freed slots are simply
zeroed in the token buffer: an all-pad row masks to an exact-zero
attention output (the masked-softmax double-where), so stale KV pages
cost nothing and need no scrubbing.
"""

from __future__ import annotations

import numpy as np


def set_index(cache, pos):
    """Rebind every ``cache_index`` leaf of a decode cache tree to the
    per-slot position vector ``pos`` (S,) — the step's single source of
    truth for where each slot writes and how far it may attend."""
    out = {}
    for key, val in cache.items():
        if isinstance(val, dict):
            out[key] = set_index(val, pos)
        elif key == "cache_index":
            out[key] = pos
        else:
            out[key] = val
    return out


def build_step(module, nslots: int, kv: int):
    """(jitted step fn, cache shape tree) for one (arch, S, Tk) cell.

    The step replicates the solo ``GreedyDecodeMixin.generate`` scan
    body exactly — same token gather, same key mask, same f32 argmax,
    same write-at-``pos+1`` — but with per-slot positions, so a slot
    admitted mid-flight produces bit-identical tokens to a solo decode
    of the same prompt (greedy only; sampling stays on the solo path).
    """
    import jax
    import jax.numpy as jnp

    decode_mod = module.clone(decode=True)
    cache_shapes = jax.eval_shape(
        decode_mod.init, jax.random.PRNGKey(0),
        jnp.zeros((nslots, kv), jnp.int32),
    )["cache"]

    def step(variables, cache, buf, pos, t0s, live):
        cache = set_index(cache, pos)
        tok = jnp.take_along_axis(buf, pos[:, None], axis=1)
        kmask = (jnp.arange(kv)[None, :] <= pos[:, None]) & (buf != 0)
        logits, mut = decode_mod.apply(
            {**variables, "cache": cache}, tok,
            positions=pos[:, None], key_mask=kmask,
            mutable=["cache"],
        )
        step_logits = logits[:, 0].astype(jnp.float32)
        nxt = jnp.argmax(step_logits, -1).astype(jnp.int32)
        nxt_pos = pos + 1
        prev = jnp.take_along_axis(buf, nxt_pos[:, None], axis=1)[:, 0]
        # ``live`` gates the write: a free slot's buffer row stays
        # all-pad (its attention mask stays empty), and a slot still
        # prefilling copies the NEXT prompt token instead of the
        # model's prediction — identical to the solo scan's
        # ``i + 1 >= t0`` select.
        col = jnp.where(live & (nxt_pos >= t0s), nxt, prev)
        buf = buf.at[jnp.arange(nslots), nxt_pos].set(col)
        return mut["cache"], buf, col

    return jax.jit(step), cache_shapes


class PagePool:
    """S slots × Tk KV pages of resident decode state for one model.

    Only the owning model's decode worker thread touches a pool, so the
    pool itself is lock-free; the worker's condition variable is the
    synchronization point for admission and abort.
    """

    __slots__ = ("kv", "nslots", "max_slots", "cache", "buf", "pos",
                 "streams", "steps", "replica_idx", "pending_devtime")

    def __init__(self, kv: int, max_slots: int,
                 replica_idx: int | None = None):
        self.kv = int(kv)
        self.nslots = 0
        self.max_slots = int(max_slots)
        self.cache = None  # device tree, allocated on first admit
        self.buf = None    # (S, Tk) int32 token buffer
        self.pos = np.zeros(0, np.int32)
        self.streams: list = []
        self.steps = 0
        self.replica_idx = replica_idx
        # Step wall time not yet flushed to the devtime ledger: lazy
        # pools dispatch async and only pay the device sync on the
        # stride boundary, so per-step times are accumulated here and
        # recorded as one amortized sample at each sync.
        self.pending_devtime = 0.0

    # -- capacity ------------------------------------------------------------

    @property
    def live(self) -> int:
        return sum(1 for s in self.streams if s is not None)

    def page_bytes(self) -> int:
        """Resident KV bytes — observability for the freeing tests."""
        import jax

        if self.cache is None:
            return 0
        return sum(
            leaf.nbytes for leaf in jax.tree_util.tree_leaves(self.cache)
        )

    def _alloc(self, cache_shapes, nslots: int) -> None:
        import jax
        import jax.numpy as jnp

        def leaf(s):
            if s.ndim == 0:
                # cache_index: scalar in the shape probe, per-slot
                # vector in the pool (the batched decode branch).
                return jnp.zeros((nslots,), jnp.int32)
            return jnp.zeros(s.shape, s.dtype)

        self.cache = jax.tree_util.tree_map(leaf, cache_shapes)
        self.buf = jnp.zeros((nslots, self.kv), jnp.int32)
        self.pos = np.zeros(nslots, np.int32)
        self.streams = [None] * nslots
        self.nslots = nslots

    def _grow(self, cache_shapes, nslots: int) -> None:
        """Pad every per-slot axis up to the next slot bucket; existing
        slots keep their pages and positions bit-for-bit."""
        import jax
        import jax.numpy as jnp

        extra = nslots - self.nslots

        def pad(leaf):
            width = [(0, extra)] + [(0, 0)] * (leaf.ndim - 1)
            return jnp.pad(leaf, width)

        del cache_shapes  # same tree structure; pad in place
        self.cache = jax.tree_util.tree_map(pad, self.cache)
        self.buf = jnp.pad(self.buf, [(0, extra), (0, 0)])
        self.pos = np.concatenate(
            [self.pos, np.zeros(extra, np.int32)]
        )
        self.streams.extend([None] * extra)
        self.nslots = nslots

    # -- slot lifecycle ------------------------------------------------------

    def admit(self, stream, cache_shapes_for) -> int | None:
        """Seat ``stream`` in a free slot (growing to the next slot
        bucket if needed, up to ``max_slots``); None when full.  The
        slot's buffer row gets the prompt, position 0 — prefill runs
        through the shared step one token at a time, exactly like the
        solo scan."""
        from learningorchestra_tpu.serve.bucketing import bucket_for

        slot = None
        for i, s in enumerate(self.streams):
            if s is None:
                slot = i
                break
        if slot is None:
            if self.nslots >= self.max_slots:
                return None
            want = bucket_for(self.nslots + 1, self.max_slots)
            if self.nslots == 0:
                self._alloc(cache_shapes_for(want), want)
            else:
                self._grow(cache_shapes_for(want), want)
            slot = next(
                i for i, s in enumerate(self.streams) if s is None
            )
        row = np.zeros(self.kv, np.int32)
        row[: stream.t0] = stream.prompt
        self.buf = self.buf.at[slot].set(row)
        self.pos[slot] = 0
        self.streams[slot] = stream
        return slot

    def release(self, slot: int) -> None:
        """Free the slot and its KV pages: zeroing the buffer row
        empties the slot's attention mask, so whatever K/V the pages
        still hold is unreachable — the pages are free for the next
        admit without a scrub pass."""
        self.streams[slot] = None
        self.pos[slot] = 0
        if self.buf is not None:
            self.buf = self.buf.at[slot].set(0)
