"""ModelRegistry — trained artifacts pinned resident on device.

The async predict job pays an artifact read (dill load) plus a full
host→device parameter upload PER REQUEST.  Online serving cannot: the
registry loads a trained ``NeuralEstimator`` artifact once, places its
parameters on device, and keeps them resident across requests — the
"params live in HBM, host sees them at job edges" discipline extended
from training to serving.


- LRU with BOTH an entry cap and a byte cap (real bytes: the sum of
  parameter leaf ``nbytes`` — unlike compiled executables, parameter
  residency is exactly measurable), ``LO_TPU_SERVE_*`` knobs;
- invalidation: the owning service subscribes to artifact-change
  notifications (overwrite by a PATCH re-train, DELETE), so a resident
  model can never serve a deleted or superseded artifact's weights.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable

from learningorchestra_tpu.concurrency_rt import make_lock


class ServeError(Exception):
    """Model cannot be served (bad artifact type, no params) → 406."""


class _Resident:
    __slots__ = (
        "name", "estimator", "params", "nbytes", "loaded_at", "requests",
        "apply_fns", "apply_costs", "replica_devices", "warm_shapes",
        "decode_warm",
    )

    def __init__(self, name, estimator, params, nbytes):
        self.name = name
        self.estimator = estimator
        self.params = params
        self.nbytes = nbytes
        self.loaded_at = time.time()
        self.requests = 0
        # bucket → jitted apply, resolved once per bucket through the
        # compile cache (fingerprinting per dispatch would waste the
        # serving hot path); dies with the entry, so invalidation can
        # never serve a stale architecture's program.
        self.apply_fns: dict = {}
        # bucket → ProgramCost (obs/costs.py), memoized beside the
        # apply so the per-dispatch attribution hook never re-derives
        # a fingerprint on the hot path.
        self.apply_costs: dict = {}
        # replica index → device id ("host" when unplaced), mirrored
        # in by the fleet manager after every scale event — residency
        # listings show WHERE each model serves, not just that it is
        # resident.  Empty for single-path models.
        self.replica_devices: dict = {}
        # bucket rows → (padded shape, dtype str) recorded at dispatch
        # time — the hot bucket set a fresh replica is pre-warmed
        # against before the router may pick it.
        self.warm_shapes: dict = {}
        # (slot-bucket, kv-bucket) → True for every decode step
        # executable this model resolved — the decode leg of replica
        # pre-warm (serve/decode/engine.py); dies with the entry like
        # warm_shapes, so invalidation never warms a stale arch.
        self.decode_warm: dict = {}

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "module": type(self.estimator.module).__name__,
            "paramBytes": self.nbytes,
            "loadedAt": self.loaded_at,
            "requests": self.requests,
            "replicaDevices": {
                str(k): v for k, v in self.replica_devices.items()
            },
        }


class ModelRegistry:
    """name → resident model, LRU over entry count and parameter bytes.

    ``loader`` maps an artifact name to a trained estimator (the
    serving service binds it to the artifact store); the registry only
    owns residency.
    """

    def __init__(
        self,
        loader: Callable[[str], Any],
        *,
        max_models: int = 4,
        max_bytes: int = 1 << 30,
        on_evict: Callable[[str], None] | None = None,
    ):
        self._loader = loader
        # Fired (outside the registry lock) with each LRU-evicted
        # model's name, so per-model satellite state (the serving
        # service's MicroBatcher threads) is released with the entry.
        self._on_evict = on_evict
        self.max_models = int(max_models)
        self.max_bytes = int(max_bytes)
        self._entries: OrderedDict[str, _Resident] = OrderedDict()
        self._lock = make_lock("ModelRegistry._lock")
        # Per-name load coalescing: concurrent first requests for one
        # model must pay a single artifact read + device upload.
        self._loading: dict[str, threading.Event] = {}
        # Names invalidated/unloaded WHILE their load was in flight:
        # the finished load must not insert (its binary may predate
        # the overwrite/delete that raced it) — the caller gets its
        # one result, the next request reloads fresh.
        self._doomed: set[str] = set()
        self.loads = 0
        self.evictions = 0
        self.invalidations = 0

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _place(estimator) -> tuple[Any, int]:
        """Device-put the params once; returns (device tree, bytes)."""
        import jax

        if getattr(estimator, "params", None) is None:
            raise ServeError(
                "artifact holds no trained parameters (was it fit?)"
            )
        params = jax.device_put(estimator.params)
        nbytes = sum(
            int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree_util.tree_leaves(params)
        )
        return params, nbytes

    def _evict_locked(self) -> list[str]:
        def total():
            return sum(e.nbytes for e in self._entries.values())

        evicted: list[str] = []
        while self._entries and (
            len(self._entries) > self.max_models
            or total() > self.max_bytes
        ):
            if len(self._entries) == 1:
                break  # never evict the entry just loaded
            name, _ = self._entries.popitem(last=False)
            evicted.append(name)
            self.evictions += 1
        return evicted

    # -- public surface ------------------------------------------------------

    def get(self, name: str) -> _Resident:
        """Resident entry for ``name``, loading (once, under concurrent
        callers) on a miss."""
        while True:
            with self._lock:
                entry = self._entries.get(name)
                if entry is not None:
                    self._entries.move_to_end(name)
                    return entry
                pending = self._loading.get(name)
                if pending is None:
                    pending = self._loading[name] = threading.Event()
                    break
            pending.wait()
        try:
            estimator = self._loader(name)
            params, nbytes = self._place(estimator)
            entry = _Resident(name, estimator, params, nbytes)
        except BaseException:
            with self._lock:
                ev = self._loading.pop(name, None)
                self._doomed.discard(name)
            if ev is not None:
                ev.set()
            raise
        with self._lock:
            ev = self._loading.pop(name, None)
            self.loads += 1
            if name in self._doomed:
                # Invalidated mid-load: serve THIS caller from what
                # was read (a complete binary — the volume publish is
                # atomic) but never cache it; at most one response can
                # see superseded weights.
                self._doomed.discard(name)
                evicted = []
            else:
                self._entries[name] = entry
                self._entries.move_to_end(name)
                evicted = self._evict_locked()
        if ev is not None:
            ev.set()
        for victim in evicted:
            if self._on_evict is not None:
                try:
                    self._on_evict(victim)
                except Exception:  # noqa: BLE001 — never fail a load
                    pass
        return entry

    def peek(self, name: str) -> _Resident | None:
        """Resident entry or None — never loads (list/unload paths)."""
        with self._lock:
            return self._entries.get(name)

    def unload(self, name: str) -> bool:
        with self._lock:
            if name in self._loading:
                self._doomed.add(name)
                return True
            return self._entries.pop(name, None) is not None

    def invalidate(self, name: str) -> bool:
        """Drop a resident model whose backing artifact changed
        (overwrite/delete) — the next request reloads or 404s.  A load
        in flight for the name is doomed: its result serves only the
        caller that started it, never the cache."""
        with self._lock:
            hit = self._entries.pop(name, None) is not None
            if name in self._loading:
                self._doomed.add(name)
                hit = True
            if hit:
                self.invalidations += 1
            return hit

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._doomed.update(self._loading)

    def list(self) -> list[dict]:
        with self._lock:
            return [e.to_dict() for e in self._entries.values()]

    def stats(self) -> dict:
        with self._lock:
            return {
                "residentModels": len(self._entries),
                "maxModels": self.max_models,
                "residentBytes": sum(
                    e.nbytes for e in self._entries.values()
                ),
                "maxBytes": self.max_bytes,
                "loads": self.loads,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
            }
