"""Power-of-two shape buckets + row padding.

XLA compiles one executable per input shape.  Online traffic arrives in
arbitrary row counts, so dispatching raw request shapes would compile an
executable per DISTINCT count — unbounded compile churn, exactly the
failure mode the pjit serving discipline avoids by keeping a small fixed
set of shapes hot (PAPERS.md: Gemma-on-TPU serving, pjit dispatch).
Rounding every dispatch up to the next power of two bounds the whole
deployment at ``log2(max_batch)+1`` executables per model, at a worst
case of <2x padded compute.

Shared by the serving path (MicroBatcher) and ``NeuralEstimator.predict``
(which pads its ragged tail batch up to ``batch_size`` so repeat predicts
compile at most one shape per batch size).
"""

from __future__ import annotations

import numpy as np


def bucket_for(rows: int, max_bucket: int) -> int:
    """Smallest power of two >= ``rows``, capped at ``max_bucket``.

    ``max_bucket`` itself is always a legal bucket even when it is not a
    power of two (the cap wins: dispatches never exceed it).
    """
    if rows <= 0:
        raise ValueError(f"rows must be positive, got {rows}")
    if rows >= max_bucket:
        return max_bucket
    return min(1 << (rows - 1).bit_length(), max_bucket)


def bucket_sizes(max_bucket: int) -> list[int]:
    """Every bucket ``bucket_for`` can produce for this cap — the bound
    on compiled executables per model (observability/tests)."""
    out = []
    b = 1
    while b < max_bucket:
        out.append(b)
        b <<= 1
    out.append(max_bucket)
    return out


def pad_rows(x: np.ndarray, target: int) -> np.ndarray:
    """Pad ``x`` along axis 0 up to ``target`` rows by repeating row 0.

    Row repetition (not zeros) keeps pad rows inside the input
    distribution — a zero row can be out-of-vocabulary garbage for
    token models, and while outputs for pad rows are discarded, feeding
    NaN-producing garbage through the network risks poisoning XLA's
    whole-batch fast paths.  Callers slice the first ``len(x)`` output
    rows; per-row independence holds for the zoo (GroupNorm, no batch
    statistics).
    """
    n = x.shape[0]
    if n == 0:
        raise ValueError("cannot pad an empty batch")
    if n > target:
        raise ValueError(f"batch of {n} rows exceeds bucket {target}")
    if n == target:
        return x
    pad = np.broadcast_to(x[:1], (target - n, *x.shape[1:]))
    return np.concatenate([x, pad], axis=0)
