"""ServingService — the REST-facing facade over registry + batchers.

Ties a :class:`~learningorchestra_tpu.serve.registry.ModelRegistry`
(artifact → device-resident params) to one
:class:`~learningorchestra_tpu.serve.batcher.MicroBatcher` per served
model, resolves each bucket's jitted ``apply`` through the process-wide
compiled-program cache (``compile_cache.apply_program_key`` — one
executable per (architecture, bucket) for the whole deployment), and
exposes the synchronous predict the API layer serves at
``POST /serve/<model>/predict``.

Invalidation: subscribes to the service context's artifact-change
notifications, so a PATCH re-train or DELETE of a served artifact drops
its resident weights before the next request.
"""

from __future__ import annotations

import os
import time

import numpy as np

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.jobs.leases import LeaseTimeout
from learningorchestra_tpu.obs.metrics import get_registry
from learningorchestra_tpu.serve.batcher import MicroBatcher
from learningorchestra_tpu.serve.fleet.manager import FleetManager
from learningorchestra_tpu.serve.registry import ModelRegistry, ServeError

#: Steps of serving_* scalar history kept (and rewritten per snapshot).
_SCALAR_WINDOW = 512


class _PredictHist:
    """Identity-cached handle on the current registry's per-model
    predict latency histogram — the API server's ``_obs_handles``
    rebind idiom, so a ``reset_registry()`` mid-life re-homes the
    series while the steady state pays one identity check instead of
    a name lookup per predict.  The rollup engine derives windowed
    per-model quantiles from this family's bucket deltas and the
    predict-latency SLO reads good/bad fractions off the same series.
    Cardinality is bounded by the serving registry's max_models cap;
    no-op when LO_TPU_OBS_ENABLED=0."""

    __slots__ = ("_reg", "_hist", "_bound")

    def __init__(self):
        self._reg = None
        self._hist = None
        self._bound: dict = {}

    def observe(self, dt_s: float, model: str) -> None:
        reg = get_registry()
        if reg is not self._reg:
            self._hist = reg.histogram(
                "lo_serving_predict_duration_seconds",
                "End-to-end predict latency per served model "
                "(queue wait + coalesce + jitted apply + handoff).",
                labels=("model",),
            )
            self._bound = {}
            self._reg = reg
        # Per-model bound series (<= max_models entries): the steady
        # state is one dict hit + Histogram series update.
        bound = self._bound.get(model)
        if bound is None:
            if len(self._bound) >= 256:
                # Lifetime guard: max_models bounds CONCURRENT models,
                # not every name ever served — a churny deployment
                # must not grow this cache forever.
                self._bound.clear()
            bound = self._bound[model] = self._hist.bind(model=model)
        bound.observe(dt_s)


_predict_hist = _PredictHist()


class ServingService:
    def __init__(self, ctx, monitoring_root: str | None = None):
        self.ctx = ctx
        self.cfg = ctx.config.serve
        self.monitoring_root = monitoring_root
        self.registry = ModelRegistry(
            self._load_estimator,
            max_models=self.cfg.max_models,
            max_bytes=self.cfg.max_bytes,
            # An LRU-evicted model's batcher (worker thread + stats)
            # must die with its entry, or serving N distinct models
            # over a process lifetime leaks N threads.
            on_evict=self._teardown_model,
        )
        self._batchers: dict[str, MicroBatcher] = {}
        # Fleet serving (serve/fleet/): per-model replica sets over
        # leased chips + the shared autoscaler.  Dormant (one dict
        # read per predict, no thread) until a model's replica bounds
        # allow max > 1.
        self.fleet = FleetManager(self)
        # Streaming decode (serve/decode/): resident KV page pools +
        # continuous batching for GreedyDecodeMixin models.  Dormant
        # (no thread, no pools) until the first /generate.
        from learningorchestra_tpu.serve.decode import DecodeEngine

        self.decode = DecodeEngine(self)
        self._lock = make_lock("ServingService._lock")
        self._closed = False
        # tfevents snapshot state: a fixed wall_time keeps one stable
        # events file that each snapshot rewrites with the (windowed)
        # history; the lock serializes concurrent monitoring polls —
        # two truncating writers on one file would interleave records
        # and break the CRC framing.
        self._t0 = time.time()
        self._scalar_history: dict[str, list] = {}
        self._scalar_lock = make_lock("ServingService._scalar_lock")
        ctx.add_artifact_change_listener(self._on_artifact_changed)

    # -- model residency -----------------------------------------------------

    def _load_estimator(self, name: str):
        from learningorchestra_tpu.services.context import ValidationError
        from learningorchestra_tpu.train.neural import NeuralEstimator

        meta = self.ctx.require_finished_parent(name)
        instance = self.ctx.volumes.read_object(meta.get("type", ""), name)
        if not isinstance(instance, NeuralEstimator):
            raise ValidationError(
                f"artifact {name!r} is not a neural model binary "
                f"({type(instance).__name__}); only NeuralEstimator "
                "artifacts are servable"
            )
        return instance

    def load(self, name: str) -> dict:
        """Pin ``name`` resident (idempotent) — the explicit warm-up the
        ops path uses before pointing traffic at a model."""
        return self.registry.get(name).to_dict()

    def unload(self, name: str) -> bool:
        self._teardown_model(name, keep_bounds=False)
        return self.registry.unload(name)

    def list_loaded(self) -> list[dict]:
        return self.registry.list()

    def _on_artifact_changed(self, name: str) -> None:
        """Artifact overwritten (re-train) or deleted: resident weights
        are stale — drop them; the next request reloads or 404s.  A
        DELETED artifact also forgets its fleet bounds — a future
        model reusing the name must not silently inherit them and
        fleet itself onto leased chips — while an overwrite keeps
        them, so a re-trained model comes back at its configured
        scale."""
        gone = not self.ctx.artifacts.metadata.exists(name)
        if self.registry.invalidate(name) or gone:
            self._teardown_model(name, keep_bounds=not gone)

    def _drop_batcher(self, name: str) -> None:
        """Discard the classic single-path batcher (teardown/unload
        paths).  NOT the fleet cutover — that goes through
        :meth:`retire_single_path`, which also carries the batcher's
        lifetime counters into the replica set."""
        with self._lock:
            batcher = self._batchers.pop(name, None)
        if batcher is not None:
            batcher.close()

    def _teardown_model(self, name: str, *, keep_bounds: bool = True
                        ) -> None:
        """Release everything serving ``name``: the single-path
        batcher AND any fleet replica set (drained, chips released).
        ``keep_bounds`` survives invalidation/eviction so a re-trained
        model comes back at its configured scale; an explicit unload
        forgets the model entirely."""
        self._drop_batcher(name)
        # Decode pools hold the stale architecture's KV shapes and
        # step closures — in-flight streams fail fast, the next
        # /generate rebuilds against the reloaded artifact.
        self.decode.drop_model(name)
        self.fleet.drop(name, keep_bounds=keep_bounds)

    # -- predict -------------------------------------------------------------

    def _batcher_for(self, name: str) -> MicroBatcher:
        with self._lock:
            batcher = self._batchers.get(name)
            if batcher is None:
                if self._closed:
                    raise RuntimeError("serving is shut down")
                if self.fleet.engaged(name):
                    # Raced a fleet enable between the predict's
                    # routing check and here: refuse retriably (429 +
                    # Retry-After) instead of resurrecting the batcher
                    # the fleet just retired — the retry routes onto
                    # the replicas.
                    from learningorchestra_tpu.serve.batcher import (
                        BatcherClosed,
                    )

                    raise BatcherClosed(
                        f"model {name!r} is moving to fleet serving; "
                        "retry"
                    )
                batcher = self._batchers[name] = MicroBatcher(
                    lambda padded, _n=name: self._dispatch(_n, padded),
                    max_batch=self.cfg.max_batch,
                    max_queue=self.cfg.max_queue,
                    flush_ms=self.cfg.flush_ms,
                    name=name,
                )
            return batcher

    def _dispatch(self, name: str, padded: np.ndarray, replica=None):
        """Run one padded bucket through the cache-resolved apply.

        Resolving the registry entry HERE (not at batcher creation)
        means an invalidation between requests serves the reloaded
        artifact's weights, never a stale closure's.

        ``replica`` (a fleet Replica) redirects only the DATA — its
        device-placed parameter copy and inputs — never the program:
        every replica of an architecture resolves the same
        (arch, bucket) executable from the compile cache, so scaling
        1→N adds zero misses to THIS cache.  (XLA itself still
        compiles per device underneath the shared jitted callable —
        with ``LO_TPU_AOT_REPLICA_PREWARM`` on, a fresh replica pays
        that device-side warm-up against the recorded hot bucket set
        BEFORE the router may pick it; see
        :meth:`replica_warmup_factory`.)"""
        import jax
        import jax.numpy as jnp

        from learningorchestra_tpu import faults
        from learningorchestra_tpu.obs import costs as obs_costs
        from learningorchestra_tpu.train import compile_cache as cc

        # Chaos probe at the batch boundary: one injected failure
        # fails every request coalesced into THIS dispatch (the real
        # blast radius of a device fault mid-batch), leaving the
        # batcher worker and later dispatches healthy.
        faults.hit("serve.apply")
        entry = self.registry.get(name)
        rows = padded.shape[0]
        apply = entry.apply_fns.get(rows)
        if apply is None:
            key = cc.apply_program_key(
                entry.estimator.module, rows=rows
            )
            label = (
                f"serve:{type(entry.estimator.module).__name__}"
                f":b{rows}"
            )

            def builder():
                from learningorchestra_tpu.train.neural import (
                    _probe_program_cost,
                )

                jitted = jax.jit(entry.estimator.module.apply)
                # Cost probe on the build-once path (the one shared
                # wrapper, train/neural.py): the bucket's flops/HBM
                # land in the program ledger, so every later dispatch
                # attributes with real numerators.  The lowering runs
                # on host avatars with no mesh — collective-free by
                # construction — so the numbers stay honest when a
                # SHARDED replica later runs this bucket under GSPMD
                # (lo_serving_bucket_* must not book collective flops).
                _probe_program_cost(
                    key, label, jitted,
                    lambda: (entry.params, padded),
                    collectives_excluded=True,
                )
                return jitted

            apply = entry.apply_fns[rows] = (
                cc.get_cache().get_or_build(key, builder, label=label)
            )
        # Record the bucket for replica pre-warm (shape + dtype is
        # all a dummy dispatch needs); dies with the entry alongside
        # apply_fns, so invalidation never warms a stale architecture.
        entry.warm_shapes[rows] = (padded.shape, str(padded.dtype))
        if replica is not None:
            # Hand place() the HOST array: one host→replica-device
            # transfer, not host→default-device→replica-device.
            params, x = replica.place(entry, padded)
        else:
            params, x = entry.params, jnp.asarray(padded)
        if not obs_costs.enabled():
            return apply(params, x)
        # Per-dispatch device-time attribution, sampled: only a
        # dispatch the stride selects pays the sync (the consumer
        # blocks on the result right after, so steady-state throughput
        # is unmoved; sampled-out dispatches keep jax's async
        # pipelining).  Books the interval against the model and shape
        # bucket — the fleet's replica dispatches land here too, so
        # per-model ledgers cover single-path and fleet serving alike.
        led = obs_costs.devtime()
        weight = led.will_record(name)
        if not weight:
            return apply(params, x)
        cost = self._apply_cost(entry, rows)
        t0 = time.perf_counter()
        out = apply(params, x)
        try:
            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001
            pass
        led.record_model(
            weight, time.perf_counter() - t0,
            cost.flops if cost is not None else None,
            cost.bytes_accessed if cost is not None else None,
            name, rows,
        )
        return out

    @staticmethod
    def _apply_cost(entry, rows: int):
        """The (arch, bucket) ProgramCost for attribution, memoized on
        the registry entry next to the apply itself.  A ledger MISS
        memoizes too (False sentinel): analysis happens at build time,
        before any dispatch, so a missing record stays missing — and
        re-deriving the fingerprint per dispatch is exactly the hot-
        path cost the memo exists to avoid."""
        cost = entry.apply_costs.get(rows)
        if cost is None:
            from learningorchestra_tpu.obs import costs as obs_costs
            from learningorchestra_tpu.train import compile_cache as cc

            cost = obs_costs.get_ledger().get(
                cc.apply_program_key(entry.estimator.module, rows=rows)
            )
            entry.apply_costs[rows] = cost if cost is not None else False
        return cost or None

    def replica_dispatch_factory(self, name: str):
        """Per-replica dispatch binder for the fleet manager: same
        registry/compile-cache path as the single-batcher dispatch,
        plus the replica's device placement.  Binder ONLY — the
        single-path batcher is retired via :meth:`retire_single_path`
        after the first replica actually places, so a failed scale-up
        (chip pool exhausted) leaves the model serving exactly as
        before instead of knocking it off the air."""
        def factory(replica):
            return lambda padded: self._dispatch(
                name, padded, replica=replica
            )

        return factory

    def replica_warmup_factory(self, name: str):
        """Pre-warm binder for the fleet manager, or None when
        ``LO_TPU_AOT_REPLICA_PREWARM`` is off.  The returned callable
        runs dummy dispatches for every bucket the model has actually
        served (``entry.warm_shapes``) through the new replica —
        paying XLA's per-device executable load/compile BEFORE the
        P2C router can pick the replica, so scale-up under a traffic
        spike no longer exposes cold p99.  Warm-up failures are the
        caller's to log: a replica that can't warm still serves (cold)
        rather than stranding acquired chips."""
        from learningorchestra_tpu.config import get_config

        try:
            if not get_config().aot.replica_prewarm:
                return None
        except Exception:  # noqa: BLE001 — config breakage → no warmup
            return None

        def warm(replica):
            try:
                entry = self.registry.get(name)
            except Exception:  # noqa: BLE001 — gone → nothing to warm
                return
            for rows, (shape, dtype) in sorted(
                entry.warm_shapes.items()
            ):
                dummy = np.zeros(shape, dtype=dtype)
                self._dispatch(name, dummy, replica=replica)
            # Decode leg: replay recorded (slot, kv) step executables
            # so streamed generation never pays a cold replica either.
            self.decode.warm_replica(name, replica)

        return warm

    def pop_single_path(self, name: str) -> MicroBatcher | None:
        """Detach (NOT close) the model's single-path batcher — THE
        fleet cutover entry point (``FleetManager._finish_cutover``).
        The manager absorbs its counters into the live set, registers
        the set, and only then drains the detached batcher: predicts
        route onto replicas immediately instead of stalling behind
        the old path's flush."""
        with self._lock:
            return self._batchers.pop(name, None)

    @staticmethod
    def _as_batch(instances) -> np.ndarray:
        """Request JSON → input batch, REST dtype discipline: float
        features land f32 (f64 would retrace against f32-traced
        programs), integer features stay int (token models)."""
        try:
            x = np.asarray(instances)
        except (ValueError, TypeError) as exc:
            # Ragged rows (inhomogeneous shapes) are a malformed
            # request body → 406, not an unhandled 500.
            raise ServeError(
                f"'instances' is not a rectangular array: {exc}"
            ) from None
        if x.ndim == 0:
            raise ServeError("'instances' must be a non-empty array")
        if x.ndim == 1:
            # A single instance's feature vector: serve it as one row.
            x = x[None, :] if x.shape[0] else x
        if x.shape[0] == 0:
            raise ServeError("'instances' must be a non-empty array")
        if np.issubdtype(x.dtype, np.floating):
            return x.astype(np.float32)
        if np.issubdtype(x.dtype, np.integer):
            return x.astype(np.int32)
        raise ServeError(
            f"instances dtype {x.dtype} is not numeric"
        )

    def predict(self, name: str, instances) -> dict:
        """Synchronous low-latency predict: coalesced, bucketed, split.

        Raises ``QueueFull`` under backpressure (API → 429) and the
        context's NotFound/Validation errors for bad models (404/406).
        """
        x = self._as_batch(instances)
        entry = self.registry.get(name)  # load-before-queue: 404 fast
        t0 = time.perf_counter()
        try:
            rs = self.fleet.routing_set(name)
        except LeaseTimeout:
            # A PARTIAL cutover registers a routable set before
            # re-raising — serve on it; otherwise the single-path
            # batcher is only retired AFTER the first replica places,
            # so degrade to it rather than going dark.  Only with
            # neither does the 503 + Retry-After surface.
            rs = self.fleet.registered_set(name)
            if rs is None and self._batchers.get(name) is None:
                raise
        if rs is not None:
            out, replica = rs.submit(x)
            entry.requests += 1
            dt = time.perf_counter() - t0
            _predict_hist.observe(dt, model=name)
            return {
                "model": name,
                "predictions": out.tolist(),
                "latencyMs": round(dt * 1e3, 3),
                "replica": replica.idx,
                "device": replica.device_id or "host",
            }
        out = self._batcher_for(name).submit(x)
        entry.requests += 1
        dt = time.perf_counter() - t0
        _predict_hist.observe(dt, model=name)
        return {
            "model": name,
            "predictions": out.tolist(),
            "latencyMs": round(dt * 1e3, 3),
        }

    def generate(self, name: str, prompts, **kwargs):
        """Streaming/batch LM generation — the decode engine's facade
        (``POST /serve/<model>/generate``).  Returns a dict for
        non-stream requests, a :class:`~learningorchestra_tpu.serve.
        decode.DecodeStream` (the SSE payload) for ``stream=True``."""
        return self.decode.generate(name, prompts, **kwargs)

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            per_model = {
                name: b.stats() for name, b in self._batchers.items()
            }
        # Fleet models surface through the SAME per-model stats shape
        # (replica batchers merged), so aggregate()/tfevents/Prometheus
        # see one consistent view; per-replica detail rides the
        # dedicated "fleet" key.
        for name, rs in self.fleet.sets_snapshot():
            per_model[name] = rs.merged_stats()
        return {
            "registry": self.registry.stats(),
            "models": per_model,
            "fleet": self.fleet.snapshot(),
            "decode": self.decode.stats(),
            "config": {
                "maxBatch": self.cfg.max_batch,
                "maxQueue": self.cfg.max_queue,
                "flushMs": self.cfg.flush_ms,
                "maxModels": self.cfg.max_models,
                "maxBytes": self.cfg.max_bytes,
                "retryAfterS": self.cfg.retry_after_s,
            },
        }

    def aggregate(self, stats: dict | None = None) -> dict:
        """Cross-model roll-up over :meth:`stats` — the ONE place the
        per-batcher aggregation lives; the tfevents snapshot below and
        the API server's Prometheus collector both consume it, so a
        new batcher stat lands on every surface from here."""
        if stats is None:
            stats = self.stats()
        agg = {"requests": 0, "rows": 0, "batches": 0, "overflows": 0,
               "padded_rows": 0, "queue_depth": 0}
        occ: list[float] = []
        quantiles = {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        for mstats in stats["models"].values():
            agg["requests"] += mstats["requests"]
            agg["rows"] += mstats["rows"]
            agg["batches"] += mstats["batches"]
            agg["overflows"] += mstats["overflows"]
            agg["padded_rows"] += mstats["paddedRows"]
            agg["queue_depth"] += mstats["queueDepth"]
            occ.append(mstats["batchOccupancy"])
            for q in quantiles:
                # Max over models: the worst served model is the one
                # an SLO cares about.
                quantiles[q] = max(quantiles[q], mstats["latencyMs"][q])
        agg["occupancy"] = (
            round(sum(occ) / len(occ), 4) if occ else 0.0
        )
        agg["quantiles"] = quantiles
        agg["resident_models"] = stats["registry"]["residentModels"]
        agg["resident_bytes"] = stats["registry"]["residentBytes"]
        return agg

    def snapshot_scalars(self, stats: dict | None = None) -> dict:
        """Append current aggregate stats to the serving history and
        (when a monitoring root exists) rewrite them as ``serving_*``
        tfevents scalars — each poll of the monitoring endpoint adds
        one step, so TensorBoard shows serving health over time.
        Pass ``stats`` when the caller already computed :meth:`stats`
        (the monitoring route serves both) to avoid taking every
        batcher lock twice per poll."""
        a = self.aggregate(stats)
        agg = {
            "serving_requests": a["requests"],
            "serving_rows": a["rows"],
            "serving_batches": a["batches"],
            "serving_overflows": a["overflows"],
            "serving_queue_depth": a["queue_depth"],
            "serving_batch_occupancy": a["occupancy"],
            "serving_p50_ms": a["quantiles"]["p50"],
            "serving_p95_ms": a["quantiles"]["p95"],
            "serving_p99_ms": a["quantiles"]["p99"],
            "serving_resident_models": a["resident_models"],
            "serving_resident_bytes": a["resident_bytes"],
        }
        # Cost-accounting scalars (obs/costs.py): attributed device
        # seconds across served models, and achieved-vs-peak MFU when
        # the operator configured the chip's peak FLOP/s.
        try:
            from learningorchestra_tpu.obs import costs as obs_costs

            totals = obs_costs.serving_totals()
            agg["serving_device_time_s"] = totals["deviceTimeS"]
            if "mfu" in totals:
                agg["serving_mfu"] = totals["mfu"]
        except Exception:  # noqa: BLE001 — scalars must never fail
            pass  # the monitoring poll
        with self._scalar_lock:
            for key, val in agg.items():
                steps = self._scalar_history.setdefault(key, [])
                steps.append(float(val))
                # Bounded window: a long-lived server polled every few
                # seconds must not grow this (or the rewritten events
                # file) without limit.
                if len(steps) > _SCALAR_WINDOW:
                    del steps[:-_SCALAR_WINDOW]
            if self.monitoring_root:
                from learningorchestra_tpu.services.tfevents import (
                    write_scalars,
                )

                logdir = os.path.join(
                    str(self.monitoring_root), "serving"
                )
                try:
                    # Fixed wall_time → fixed file name: every
                    # snapshot rewrites ONE events file with the
                    # windowed history.
                    write_scalars(
                        logdir, self._scalar_history,
                        wall_time=self._t0,
                    )
                except OSError:
                    pass  # observability must never fail the poll
        return agg

    def close(self) -> None:
        with self._lock:
            self._closed = True
            batchers = list(self._batchers.values())
            self._batchers.clear()
        # Decode first: in-flight streams get a terminal event before
        # their replicas/chips go away under them.
        self.decode.close()
        # Fleet next: stops the autoscaler (no scale decisions against
        # a closing service), drains replica batchers, releases chips.
        self.fleet.close()
        for batcher in batchers:
            batcher.close()
        self.registry.clear()
