"""FleetManager — per-model ReplicaSets + the shared autoscaler.

The glue between the serving service and the fleet: owns one
:class:`~learningorchestra_tpu.serve.fleet.replicaset.ReplicaSet` per
fleet-enabled model, the per-model min/max bounds (REST-configurable,
surviving artifact invalidation so a re-trained model comes back at its
configured scale), and the one
:class:`~learningorchestra_tpu.serve.fleet.autoscaler.Autoscaler`
thread — started lazily the first time any model can actually scale
(max > 1), so a default single-replica deployment runs zero extra
threads and ``predict`` pays one dict lookup.

Fleet routing engages per model: either the deployment-wide default
(``LO_TPU_FLEET_MAX > 1`` puts every served model on the fleet path)
or a per-model ``POST /serve/<model>/replicas`` body.  Everything else
— artifact invalidation, LRU eviction, unload — flows through
``drop()``: the set drains and releases its chips; bounds survive
unless the unload was explicit.
"""

from __future__ import annotations

import threading
import time

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.serve.fleet.autoscaler import Autoscaler
from learningorchestra_tpu.serve.fleet.replicaset import ReplicaSet


class FleetManager:
    def __init__(self, service):
        self.service = service
        self.cfg = service.ctx.config.fleet
        self._sets: dict[str, ReplicaSet] = {}
        # Per-model replica bounds.  Value semantics: a (min, max)
        # tuple is an explicit fleet opt-in; None is an explicit
        # OPT-OUT (a dissolved model stays single-path even when the
        # deployment default LO_TPU_FLEET_MAX would fleet it); an
        # absent key falls back to the deployment default.
        self._bounds: dict[str, tuple[int, int] | None] = {}
        # Per-model chips-per-replica overrides (POST body
        # ``devicesPerReplica``); absent falls back to the deployment
        # default LO_TPU_FLEET_DEVICES_PER_REPLICA.  Fixed while a set
        # is live — changing the shard width means re-placing every
        # replica, so configure() rejects it until a dissolve.
        self._shards: dict[str, int] = {}
        self._lock = make_lock("FleetManager._lock")
        # Per-model creation coalescing (the ModelRegistry idiom): a
        # set is only REGISTERED once its first replica is placed, so
        # concurrent predicts during the (possibly seconds-long) lease
        # wait park on the creator's event instead of finding an
        # empty set and shedding 429.
        self._creating: dict[str, threading.Event] = {}
        # Names whose in-flight creation a concurrent dissolve/drop
        # cancelled: the creator must NOT register its set (it would
        # resurrect a fleet the operator just tore down, chip lease
        # and all).  Entries live only while a creation is in flight.
        self._cancel_create: set[str] = set()
        # model -> monotonic deadline of a placement-failure cooldown:
        # while it runs, routing_set sends traffic straight to the
        # single-path batcher instead of serializing every predict
        # through a doomed lease_timeout_s wait against an exhausted
        # chip pool.  Explicit POSTs bypass it (configure -> ensure).
        self._cooldown: dict[str, float] = {}
        # model -> [scale_ups, scale_downs] accumulated from CLOSED
        # sets, so the counter-typed scale-events exposition survives
        # dissolve/invalidation instead of resetting mid-series.
        # Pruned with the bounds lifecycle (explicit unload/deletion
        # forgets the model entirely) — bounded by configured models.
        self._scale_totals: dict[str, list] = {}
        self._closed = False
        self.autoscaler = Autoscaler(self, self.cfg)

    # -- the predict hot path ------------------------------------------------

    def routing_set(self, name: str) -> ReplicaSet | None:
        """The set to route ``name`` through, or None for the classic
        single-batcher path.  One GIL-atomic dict read when fleet
        serving is not in play — the disabled path's whole cost."""
        rs = self._sets.get(name)
        if rs is not None:
            return rs
        if self._mode(name) is None:
            return None
        if time.monotonic() < self._cooldown.get(name, 0.0):
            return None  # recent placement failure: stay single-path
        return self.ensure(name)

    def registered_set(self, name: str) -> ReplicaSet | None:
        """An already-live set only — never creates.  The predict
        path's LeaseTimeout fallback uses this: a PARTIAL cutover
        registers a routable set before re-raising, and that set must
        serve the triggering request rather than a spurious 503."""
        return self._sets.get(name)

    def _mode(self, name: str) -> tuple[int, int] | None:
        """The bounds ``name`` serves under: a tuple means fleet,
        None means single-path (explicit opt-out, or deployment
        defaults that don't fleet)."""
        if name in self._bounds:
            return self._bounds[name]
        if self.cfg.max_replicas > 1:
            return (self.cfg.min_replicas, self.cfg.max_replicas)
        return None

    def engaged(self, name: str) -> bool:
        """True once ``name`` is (or is becoming) fleet-served — the
        single-path batcher must not be (re)created past this point:
        a predict racing fleet creation would otherwise resurrect the
        just-dropped batcher, leak its worker thread, and serve that
        one request off-fleet.

        Exception: during a placement-failure COOLDOWN a fleet-bound
        model with no set is allowed its single-path batcher — a
        model that never served before must not go dark just because
        the chip pool is exhausted; when a replica finally places,
        the cutover retires that batcher and carries its counters."""
        if name in self._sets or name in self._creating:
            return True
        if self._mode(name) is None:
            return False
        return time.monotonic() >= self._cooldown.get(name, 0.0)

    def ensure(self, name: str, *,
               bypass_cooldown: bool = False) -> ReplicaSet | None:
        """The model's ReplicaSet, created at its min scale on first
        need (first routed predict, or a bounds POST).

        One creator per model at a time; the others wait and re-check.
        The set enters ``_sets`` only AFTER its first replica is
        placed, so no predict can ever observe a zero-replica set —
        and a failed placement (LeaseTimeout) registers nothing AND
        leaves the single-path batcher un-retired, so the model keeps
        serving on it (predict catches the LeaseTimeout and degrades)
        while later requests re-attempt the lease."""
        while True:
            rs = self._sets.get(name)
            if rs is not None:
                return rs
            if not bypass_cooldown and time.monotonic() < (
                self._cooldown.get(name, 0.0)
            ):
                # The creator we waited on just failed its lease: the
                # whole burst degrades to the single-path batcher at
                # once — waiters must not each become the next creator
                # and serially re-pay a doomed lease_timeout_s wait.
                # (Explicit POSTs bypass: the operator asked.)
                return None
            with self._lock:
                if self._closed:
                    return None
                rs = self._sets.get(name)
                if rs is not None:
                    return rs
                pending = self._creating.get(name)
                if pending is None:
                    pending = self._creating[name] = threading.Event()
                    break
            pending.wait(self.cfg.lease_timeout_s + 1.0)
        try:
            with self._lock:
                mode = self._mode(name)
            if mode is None:
                # Dissolved between the routing check and here: the
                # model stays on the classic path.
                return None
            mn, mx = mode
            rs = ReplicaSet(
                name,
                self.service.cfg,
                self.service.ctx.leaser,
                self.service.replica_dispatch_factory(name),
                min_replicas=mn,
                max_replicas=mx,
                lease_timeout_s=self.cfg.lease_timeout_s,
                router_seed=self.cfg.router_seed,
                devices_per_replica=self.devices_per_replica(name),
                # getattr: test stubs provide only the dispatch seam.
                warmup=(
                    self.service.replica_warmup_factory(name)
                    if hasattr(self.service, "replica_warmup_factory")
                    else None
                ),
            )
            try:
                rs.scale_to(rs.min_replicas, reason="ensure")
            except BaseException:
                if rs.size == 0:
                    # Nothing placed: the single-path batcher was
                    # never touched, so the model keeps serving
                    # exactly as before this failed cutover.  Arm the
                    # cooldown so routed predicts stop paying a
                    # doomed lease wait each until the pool recovers.
                    with self._lock:
                        self._cooldown[name] = (
                            time.monotonic()
                            + self.cfg.lease_timeout_s
                        )
                    rs.close()
                    raise
                # Partially placed (min > 1, later leases timed out):
                # it can serve — cut over and let the autoscaler heal
                # it up to min; the CALLER still sees the error.
                self._finish_cutover(name, rs)
                raise
            if self._finish_cutover(name, rs) is None:
                return None
        finally:
            with self._lock:
                ev = self._creating.pop(name, None)
                self._cancel_create.discard(name)
            if ev is not None:
                ev.set()
        return rs

    def _finish_cutover(self, name: str,
                        rs: ReplicaSet) -> ReplicaSet | None:
        """The replica set is live: register it, THEN retire the
        single-path batcher (folding its lifetime counters into the
        set so per-model serving counters never reset mid-series),
        mirror placements, and start the autoscaler if this set can
        scale (routing_set's fast path never re-enters ensure for a
        registered set, so skipping the start here would freeze the
        set at its current size forever).  Returns None — set closed,
        chips released — when the manager shut down or a concurrent
        dissolve/drop cancelled this creation."""
        from learningorchestra_tpu.serve.fleet.replicaset import (
            _stats_delta,
        )

        # Detach the single-path batcher and absorb its counters
        # BEFORE the set becomes visible: an autoscaler tick landing
        # between registration and absorb would baseline the model's
        # sheds at zero and later read the carried historical 429s as
        # fresh saturation.
        old = self.service.pop_single_path(name)
        pre = None
        if old is not None:
            pre = old.stats()
            rs.absorb_stats(pre, overflows_were_sheds=True)
        with self._lock:
            cancelled = (
                self._closed or name in self._cancel_create
            )
            self._cancel_create.discard(name)
            if not cancelled:
                self._sets[name] = rs
                self._cooldown.pop(name, None)
        if cancelled:
            rs.close()
            if old is not None:
                old.close()
            return None
        if old is not None:
            # Drain AFTER registration — predicts already route onto
            # the replicas — then fold in whatever the drain flushed.
            old.close()
            rs.absorb_stats(
                _stats_delta(old.stats(), pre),
                overflows_were_sheds=True,
            )
        self._record_placements(name, rs)
        if rs.max_replicas > 1:
            self._maybe_start_autoscaler()
        return rs

    # -- control surface -----------------------------------------------------

    def devices_per_replica(self, name: str) -> int:
        """Chips each of ``name``'s replicas leases: the per-model
        override, else the deployment default."""
        with self._lock:
            override = self._shards.get(name)
        if override is not None:
            return override
        return max(1, int(getattr(
            self.cfg, "devices_per_replica", 1
        )))

    def configure(self, name: str, *, min_replicas=None,
                  max_replicas=None, count=None,
                  devices_per_replica=None) -> dict:
        """The POST /serve/<model>/replicas body: set bounds and/or a
        manual replica count (clamped to the bounds).  Pins the model
        resident — a bad name 404s here, before any chip is leased."""
        from learningorchestra_tpu.services.context import (
            ValidationError,
        )

        with self._lock:
            cur = self._bounds.get(name) or (
                self.cfg.min_replicas, self.cfg.max_replicas
            )
        mn = cur[0] if min_replicas is None else int(min_replicas)
        mx = cur[1] if max_replicas is None else int(max_replicas)
        if not 1 <= mn <= mx:
            raise ValidationError(
                f"replica bounds need 1 <= min <= max, got "
                f"min={mn} max={mx}"
            )
        if count is not None and int(count) < 1:
            raise ValidationError(
                f"replica count must be >= 1, got {count}"
            )
        if devices_per_replica is not None:
            dpr = int(devices_per_replica)
            if dpr < 1:
                raise ValidationError(
                    "devicesPerReplica must be >= 1, got "
                    f"{devices_per_replica}"
                )
            with self._lock:
                live = self._sets.get(name)
                if (live is not None
                        and live.devices_per_replica != dpr):
                    raise ValidationError(
                        "devicesPerReplica is fixed while a replica "
                        f"set is live ({live.devices_per_replica}); "
                        "dissolve the fleet first"
                    )
                self._shards[name] = dpr
        self.service.registry.get(name)  # 404 before leasing anything
        with self._lock:
            self._bounds[name] = (mn, mx)
            rs = self._sets.get(name)
        if rs is None:
            rs = self.ensure(name, bypass_cooldown=True)
        if rs is not None:
            # Unconditionally: ensure() may hand back a set a racing
            # creator built from STALE bounds (read before ours were
            # stored) — its live bounds must match what this request
            # just configured.
            rs.set_bounds(mn, mx)
        if rs is None:
            # Raced service shutdown: retriable (429 + Retry-After),
            # the client's failover repoint lands somewhere alive.
            from learningorchestra_tpu.serve.batcher import (
                BatcherClosed,
            )

            raise BatcherClosed("fleet manager is shut down; retry")
        target = int(count) if count is not None else rs.size
        rs.scale_to(target, reason="manual")
        self._record_placements(name, rs)
        if mx > 1:
            self._maybe_start_autoscaler()
        return self.status_for(name)

    def scale(self, name: str, n: int, *, reason: str) -> int:
        """The autoscaler's entry: scale an existing set (a dropped
        model is simply skipped — its streaks die with it)."""
        rs = self._sets.get(name)
        if rs is None:
            return 0
        result = rs.scale_to(n, reason=reason)
        self._record_placements(name, rs)
        return result

    def dissolve(self, name: str) -> bool:
        """Return a model to classic single-path serving WITHOUT
        unloading it: drain its replica set, release the chips, and
        pin an explicit opt-out so deployment-wide fleet defaults
        don't re-fleet it on the next predict — the remediation for
        'tried fleet serving, want the chips back'."""
        with self._lock:
            rs = self._sets.pop(name, None)
            if name in self._creating:
                # An in-flight creator must not register its set
                # after this teardown (it would resurrect the fleet,
                # chip lease and all).
                self._cancel_create.add(name)
            # The opt-out entry is stored only when there is a fleet
            # involvement to opt out OF — unconditionally recording
            # every name ever DELETEd would grow _bounds (and the
            # /serve/fleet bounds map) without bound.
            if rs is not None or name in self._bounds or (
                name in self._creating
                or (self.cfg.max_replicas > 1
                    and self.service.registry.peek(name) is not None)
            ):
                self._bounds[name] = None
        self.autoscaler.forget(name)
        if rs is not None:
            self._accumulate_scale_totals(name, rs)
            rs.close()
            entry = self.service.registry.peek(name)
            if entry is not None:
                # The chips just went back to the pool; a residency
                # listing must not keep advertising them.
                entry.replica_devices = {}
        return rs is not None

    def drop(self, name: str, *, keep_bounds: bool) -> bool:
        """Dissolve a model's fleet: drain batchers, release chips.
        ``keep_bounds=True`` (artifact invalidation / LRU eviction)
        lets the next predict rebuild at the configured scale;
        ``False`` (explicit unload) forgets the model entirely."""
        with self._lock:
            rs = self._sets.pop(name, None)
            if name in self._creating:
                # An in-flight creator's set must not outlive this
                # teardown (an unloaded model would come back
                # fleet-served, holding a chip).
                self._cancel_create.add(name)
            if not keep_bounds:
                self._bounds.pop(name, None)
                self._shards.pop(name, None)
                self._scale_totals.pop(name, None)
        self.autoscaler.forget(name)
        if rs is not None:
            if keep_bounds:
                self._accumulate_scale_totals(name, rs)
            rs.close()
        return rs is not None

    def _accumulate_scale_totals(self, name: str,
                                 rs: ReplicaSet) -> None:
        """Carry a closing set's scale-event counts so the exported
        counter series survives the set (a counter that vanishes or
        resets mid-series breaks rate() alerts)."""
        with self._lock:
            totals = self._scale_totals.setdefault(name, [0, 0])
            totals[0] += rs.scale_ups
            totals[1] += rs.scale_downs

    def sets_snapshot(self) -> list:
        with self._lock:
            return list(self._sets.items())

    def _maybe_start_autoscaler(self) -> None:
        if self.cfg.enabled and not self._closed:
            self.autoscaler.start()

    def _record_placements(self, name: str, rs: ReplicaSet) -> None:
        """Mirror the set's replica→device map onto the registry
        entry, so residency listings show WHERE each model serves."""
        entry = self.service.registry.peek(name)
        if entry is not None:
            entry.replica_devices = rs.placements()

    # -- observability -------------------------------------------------------

    def status_for(self, name: str) -> dict:
        with self._lock:
            rs = self._sets.get(name)
            bounds = self._bounds.get(name)
        if rs is not None:
            return rs.status()
        if bounds is None:
            return {}
        return {
            "model": name, "replicas": [], "size": 0,
            "min": bounds[0], "max": bounds[1],
            "devicesPerReplica": self.devices_per_replica(name),
            "scaleUps": 0, "scaleDowns": 0,
        }

    def snapshot(self) -> dict:
        with self._lock:
            sets = list(self._sets.values())
            bounds = dict(self._bounds)
            scale_totals = {
                name: list(t) for name, t in self._scale_totals.items()
            }
        for rs in sets:
            totals = scale_totals.setdefault(rs.name, [0, 0])
            totals[0] += rs.scale_ups
            totals[1] += rs.scale_downs
        return {
            "models": {rs.name: rs.status() for rs in sets},
            "scaleTotals": {
                name: {"up": t[0], "down": t[1]}
                for name, t in scale_totals.items()
            },
            "bounds": {
                name: (
                    {"min": b[0], "max": b[1]} if b is not None
                    else {"singlePath": True}
                )
                for name, b in bounds.items()
            },
            "defaults": {
                "min": self.cfg.min_replicas,
                "max": self.cfg.max_replicas,
            },
            "autoscaler": self.autoscaler.status(),
        }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            sets = list(self._sets.values())
            self._sets.clear()
        self.autoscaler.stop()
        for rs in sets:
            rs.close()
