"""Power-of-two-choices routing over replica queue depths.

The fleet's balancing problem is the classic one: per-request
least-loaded needs a full scan and herds onto one replica between
depth refreshes; random spreads badly under skew.  Power-of-two-choices
(sample two replicas, send to the shallower queue) gets exponentially
better max-load than random for one extra depth read — the standard
result the Gemma-on-TPU serving comparison's replica tier relies on
(PAPERS.md).

The router is deliberately dumb and fast: it ranks CANDIDATES from a
depth snapshot; the caller (``ReplicaSet.submit``) tries them in order
and only sheds (429) when every replica's bounded queue refuses the
request.  Decisions must cost microseconds — they sit in front of every
predict — so the seeded RNG is plain ``random.Random`` and the routing
fault probe (``serve.route``) is the usual one-dict-check ``hit``.

Determinism: the RNG is seeded per router, so a fixed request order
yields a fixed routing sequence — drills and the skew-bound test are
reproducible, not flaky (same discipline as faults/plane.py).
"""

from __future__ import annotations

import random
from typing import Sequence

from learningorchestra_tpu import faults


class P2CRouter:
    """Rank replica indices for one request from a depth snapshot."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def choose(self, depths: Sequence[int]) -> list[int]:
        """Candidate order for ``len(depths)`` replicas: the P2C winner
        first, its pair partner second, the rest by ascending depth.

        The chaos probe fires HERE — routing-decision time — so
        scale-up/down drills can inject latency or failure exactly
        where traffic is being spread (``serve.route`` point).
        """
        faults.hit("serve.route")
        n = len(depths)
        if n <= 1:
            return [0] * n
        if n == 2:
            a, b = 0, 1
        else:
            a = self._rng.randrange(n)
            b = self._rng.randrange(n - 1)
            if b >= a:
                b += 1
        if depths[b] < depths[a] or (
            depths[b] == depths[a] and self._rng.random() < 0.5
        ):
            a, b = b, a
        rest = [i for i in range(n) if i != a and i != b]
        rest.sort(key=depths.__getitem__)
        return [a, b, *rest]
