"""Fleet serving — the multi-replica data plane over leased chips.

Turns the one-device serving tier (serve/) fleet-shaped, the single
biggest step toward the "millions of users" north star (ROADMAP item
1): a model's resident params are replicated across chips acquired
through the lease pool, traffic spreads with power-of-two-choices on
live batcher queue depth, and a metrics-driven control loop turns
sustained saturation into replicas instead of 429s.

- :mod:`router` — ``P2CRouter``: seeded power-of-two-choices candidate
  ranking (plus the ``serve.route`` chaos point);
- :mod:`replicaset` — ``Replica``/``ReplicaSet``: per-replica chip
  lease + MicroBatcher + device-placed params, drain-before-unload
  scale-down, shared compile-cache executables (scaling adds zero
  compile misses);
- :mod:`autoscaler` — ``Autoscaler``: the control loop over the same
  queue-depth/p99/shed/traffic signals ``/metrics.prom`` exports;
- :mod:`manager` — ``FleetManager``: per-model sets + bounds + the
  lazily-started autoscaler thread.

Knobs live in config.py (``LO_TPU_FLEET_*``); REST surface is
``GET/POST /serve/<model>/replicas`` and ``GET /serve/fleet``.
"""

from learningorchestra_tpu.serve.fleet.autoscaler import Autoscaler
from learningorchestra_tpu.serve.fleet.manager import FleetManager
from learningorchestra_tpu.serve.fleet.replicaset import (
    Replica,
    ReplicaSet,
)
from learningorchestra_tpu.serve.fleet.router import P2CRouter

__all__ = [
    "Autoscaler",
    "FleetManager",
    "P2CRouter",
    "Replica",
    "ReplicaSet",
]
