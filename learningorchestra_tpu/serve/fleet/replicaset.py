"""ReplicaSet — N replicas of one served model across leased chips.

Before the fleet, a served model was one MicroBatcher dispatching on
the default device: the chip-lease subsystem and the serving registry
never met, and the only answer to saturation was 429.  A ``ReplicaSet``
pins each replica to a chip acquired through
:meth:`jobs.leases.DeviceLeaser.acquire` (held for the replica's
lifetime, not a with-block), gives it its own MicroBatcher, and routes
each request with power-of-two-choices on live batcher queue depth —
429 only when EVERY replica's bounded queue refuses the request.

Executable sharing: replicas do NOT get their own compiled programs.
The dispatch factory (bound by the serving service) resolves applies
through the process-wide compile cache keyed on (architecture, bucket),
so scaling 1→N adds zero compile-cache misses; only the parameter copy
is per-device (``Replica.place``).  On CPU-only backends leases grant
no devices and replicas share the registry's resident params — the
fleet machinery is then pure routing, which is what the unit tests and
the bench probe exercise.

Drain-before-unload: scale-down removes the victim from the routable
list FIRST, then closes its batcher (``MicroBatcher.close`` flushes
everything queued), then releases the chip.  A request that raced into
the victim either rides the final flush or gets ``BatcherClosed`` and
is re-routed to a surviving replica by :meth:`ReplicaSet.submit` — no
in-flight predict is dropped.
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.log import get_logger, kv
from learningorchestra_tpu.obs import tracing
from learningorchestra_tpu.serve.batcher import (
    BatcherClosed,
    MicroBatcher,
    QueueFull,
)
from learningorchestra_tpu.serve.fleet.router import P2CRouter

logger = get_logger("fleet")

#: Batcher lifetime-counter keys a set's retired pool accumulates.
_COUNTER_KEYS = ("requests", "rows", "batches", "paddedRows",
                 "overflows")


def _stats_delta(final: dict, pre: dict) -> dict:
    """What a batcher did AFTER the ``pre`` snapshot — stats-shaped,
    so ``absorb_stats`` takes it unchanged."""
    delta = {key: final[key] - pre[key] for key in _COUNTER_KEYS}
    pre_w = pre["batchOccupancy"] * pre["batches"]
    final_w = final["batchOccupancy"] * final["batches"]
    delta["batchOccupancy"] = (
        (final_w - pre_w) / delta["batches"] if delta["batches"] else 0.0
    )
    pre_buckets = pre["bucketHistogram"]
    delta["bucketHistogram"] = {
        bucket: count - pre_buckets.get(bucket, 0)
        for bucket, count in final["bucketHistogram"].items()
        if count - pre_buckets.get(bucket, 0)
    }
    return delta


def _shard_tree(params, devs):
    """Place a param tree across a multi-chip slice: one-axis GSPMD
    mesh, leading-dim sharding where the dim divides the slice size,
    replication elsewhere.  Returns ``(placed_tree, spec)`` where
    ``spec`` describes the layout (status surface) and carries the
    replicated input sharding under the private ``"_repl"`` key."""
    import jax
    import numpy as _np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    n = len(devs)
    mesh = Mesh(_np.array(devs), ("shard",))
    repl = NamedSharding(mesh, P())
    counts = {"sharded": 0, "replicated": 0}

    def put(leaf):
        if (getattr(leaf, "ndim", 0) >= 1
                and leaf.shape[0] >= n and leaf.shape[0] % n == 0):
            counts["sharded"] += 1
            return jax.device_put(
                leaf, NamedSharding(mesh, P("shard"))
            )
        counts["replicated"] += 1
        return jax.device_put(leaf, repl)

    placed = jax.tree_util.tree_map(put, params)
    spec = {
        "axis": "shard", "devices": n,
        "strategy": "leading-dim",
        "shardedLeaves": counts["sharded"],
        "replicatedLeaves": counts["replicated"],
        "_repl": repl,
    }
    return placed, spec


class Replica:
    """One routable copy of a served model: chip lease + batcher +
    per-device parameter placement.

    A replica may hold MORE than one chip (``devices_per_replica`` on
    the set): the lease then carries the whole slice and ``place``
    shards the parameter tree across it with a one-axis GSPMD mesh —
    leaves whose leading dim divides evenly split along it, the rest
    replicate.  The router/autoscaler/pre-warm never look inside: a
    sharded replica is one routable unit with one batcher, exactly
    like a single-chip one."""

    __slots__ = (
        "model", "idx", "device_id", "devices", "shard_spec",
        "batcher", "created_at", "warmed", "_handle", "_jax_device",
        "_jax_devices", "_device_resolved", "_placed",
    )

    def __init__(self, model: str, idx: int, handle):
        self.model = model
        self.idx = idx
        self._handle = handle
        self.devices: list[str] = (
            list(handle.devices) if handle is not None else []
        )
        self.device_id: str | None = (
            self.devices[0] if self.devices else None
        )
        # Populated on first multi-chip placement: how the param tree
        # landed on the slice (surfaced via GET /serve/<m>/replicas).
        self.shard_spec: dict | None = None
        self._jax_devices: list | None = None
        self.created_at = time.time()
        # True once the pre-warm dispatches (hot bucket set) completed
        # before the replica became routable; False means it serves
        # cold (warm-up off, no recorded buckets, or warm-up failed).
        self.warmed = False
        self.batcher: MicroBatcher | None = None
        self._jax_device = None
        self._device_resolved = False
        # (registry entry, params placed on this replica's device) —
        # keyed by entry IDENTITY so an artifact invalidation/reload
        # re-places fresh weights, never serves a stale copy.
        self._placed: tuple | None = None

    def place(self, entry, x):
        """(params, inputs) for this replica's device(s), from the
        HOST input array — one host→device transfer, never a bounce
        through the default device.  Unplaced replicas (CPU backend,
        unresolvable id) share the registry's resident tree — zero
        extra memory, shared executables (jit converts host inputs
        itself).

        Multi-chip leases shard instead of copy: the param tree lands
        on a one-axis mesh over the slice (leaves split along the
        leading dim when it divides, replicated otherwise) and the
        input is replicated — ``jax.jit`` then runs the bucket program
        under GSPMD across the slice, so a model too big for one
        chip's HBM still serves as ONE routable replica."""
        if not self._device_resolved:
            self._device_resolved = True
            if self.devices:
                from learningorchestra_tpu.jobs.leases import (
                    jax_device_for,
                )

                resolved = [jax_device_for(d) for d in self.devices]
                if all(d is not None for d in resolved):
                    self._jax_devices = resolved
                    self._jax_device = resolved[0]
        devs = self._jax_devices
        if devs is None:
            return entry.params, x
        import jax

        if len(devs) == 1:
            cached = self._placed
            if cached is None or cached[0] is not entry:
                self._placed = cached = (
                    entry, jax.device_put(entry.params, devs[0])
                )
            return cached[1], jax.device_put(x, devs[0])
        cached = self._placed
        if cached is None or cached[0] is not entry:
            placed, spec = _shard_tree(entry.params, devs)
            self.shard_spec = spec
            self._placed = cached = (entry, placed, spec["_repl"])
        return cached[1], jax.device_put(x, cached[2])

    def release(self) -> None:
        self._placed = None
        if self._handle is not None:
            self._handle.release()

    def status(self) -> dict:
        stats = self.batcher.stats() if self.batcher is not None else {}
        spec = self.shard_spec
        return {
            "replica": self.idx,
            "device": self.device_id or "host",
            "devices": self.devices or ["host"],
            "shardSpec": (
                {k: v for k, v in spec.items() if not k.startswith("_")}
                if spec is not None else None
            ),
            "createdAt": self.created_at,
            "requests": stats.get("requests", 0),
            "queueDepth": stats.get("queueDepth", 0),
            "batches": stats.get("batches", 0),
            "overflows": stats.get("overflows", 0),
            "latencyMs": stats.get("latencyMs", {}),
            "warmed": self.warmed,
        }


class ReplicaSet:
    """The per-model fleet: replica lifecycle + P2C request routing.

    ``dispatch_factory(replica)`` returns the padded-bucket dispatch
    for one replica — the serving service binds the real registry +
    compile-cache + device-placement dispatch; tests and the bench
    probe inject stubs to exercise routing/scaling without a model.
    """

    def __init__(
        self,
        name: str,
        serve_cfg,
        leaser,
        dispatch_factory: Callable[[Replica], Callable],
        *,
        min_replicas: int = 1,
        max_replicas: int = 1,
        lease_timeout_s: float = 5.0,
        router_seed: int = 0,
        warmup: Callable[[Replica], None] | None = None,
        devices_per_replica: int = 1,
    ):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min ({min_replicas}) <= max "
                f"({max_replicas})"
            )
        if int(devices_per_replica) < 1:
            raise ValueError(
                "devices_per_replica must be >= 1, got "
                f"{devices_per_replica}"
            )
        self.name = name
        self._cfg = serve_cfg
        self._leaser = leaser
        self._factory = dispatch_factory
        # Optional pre-router warm-up (serve.ServingService binds the
        # hot-bucket dummy dispatches when LO_TPU_AOT_REPLICA_PREWARM
        # is on): runs against a fresh replica BEFORE it joins the
        # routable list, so the P2C router never picks a cold device.
        self._warmup = warmup
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        # Chips per replica: > 1 turns every lease into a multi-chip
        # slice and every replica into a GSPMD-sharded one (models
        # bigger than one chip's HBM).  Fixed for the set's lifetime —
        # changing it means re-placing every param tree, i.e. a new
        # set.
        self.devices_per_replica = int(devices_per_replica)
        self.lease_timeout_s = float(lease_timeout_s)
        import zlib

        # Seed mixed with a stable CRC of the model name (the faults
        # plane's idiom): distinct models route through distinct but
        # reproducible RNG streams.
        self.router = P2CRouter(
            (int(router_seed) << 32) ^ zlib.crc32(name.encode())
        )
        self._replicas: list[Replica] = []
        self._lock = make_lock("ReplicaSet._lock")
        # Scaling is serialized separately from the routing lock: a
        # lease acquisition may block for seconds, and two concurrent
        # scalers (autoscaler tick + manual POST + lazy ensure) must
        # converge on one target instead of overshooting; routing
        # meanwhile keeps reading the replica list freely.
        self._scale_lock = make_lock("ReplicaSet._scale_lock")
        self._closed = False
        self.scale_ups = 0
        self.scale_downs = 0
        # CLIENT-VISIBLE sheds: submit exhausted every candidate and
        # raised (→ a real 429).  Deliberately distinct from the
        # per-replica batcher ``overflows``, which also count requests
        # that overflowed one replica but were re-routed and SERVED by
        # another — scaling on those would lease chips no load needs.
        self.sheds = 0
        # Lifetime counters folded in from drained (scaled-down)
        # replicas: the set's cumulative requests/overflows must stay
        # monotonic across scale cycles — a counter that regresses
        # would corrupt the autoscaler's per-tick deltas (negative
        # "served"/"shed") and move counter-typed Prometheus series
        # backwards.
        self._retired = {
            "requests": 0, "rows": 0, "batches": 0, "paddedRows": 0,
            "overflows": 0, "occ_weighted": 0.0, "buckets": {},
        }

    # -- scaling -------------------------------------------------------------

    @property
    def size(self) -> int:
        return len(self._replicas)

    def set_bounds(self, min_replicas: int, max_replicas: int) -> None:
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min ({min_replicas}) <= max "
                f"({max_replicas})"
            )
        with self._lock:
            self.min_replicas = int(min_replicas)
            self.max_replicas = int(max_replicas)

    def scale_to(self, n: int, *, reason: str = "manual") -> int:
        """Grow/shrink to ``n`` replicas (clamped to [min, max]);
        returns the resulting count.  Scale-up may raise
        ``LeaseTimeout`` when the chip pool can't place a new replica
        within the lease budget — already-added replicas stay.

        The clamp re-reads the bounds EVERY iteration: a concurrent
        ``set_bounds`` shrinking ``max`` mid-scale must re-target, not
        spin leasing-and-discarding chips forever."""
        with self._scale_lock:
            while True:
                with self._lock:
                    if self._closed:
                        return 0
                    cur = len(self._replicas)
                    target = max(
                        self.min_replicas,
                        min(self.max_replicas, int(n)),
                    )
                if cur < target:
                    if not self._add_replica(reason):
                        # Bounds shrank (or the set closed) while the
                        # lease was being placed — re-read and settle.
                        with self._lock:
                            return len(self._replicas)
                elif cur > target:
                    self._remove_replica(reason)
                else:
                    return cur

    def _add_replica(self, reason: str) -> bool:
        with self._lock:
            # Lowest free index, NOT a monotonic counter: replica
            # indices are Prometheus label values, and a fleet
            # oscillating under the autoscaler for days must cycle
            # through a bounded label set (<= max_replicas distinct
            # values), not mint r47, r48, ... forever.
            live = {r.idx for r in self._replicas}
            idx = next(
                i for i in range(len(live) + 1) if i not in live
            )
        # "@" keeps the label OUT of the deadline watchdog's revoke
        # namespace: revoke(job) matches "<job>" or "<job>:*", and job
        # names can be any NAME-regex token ("serve" included) but can
        # never contain "@" — a job named "serve" expiring its
        # deadline must not force-free every fleet replica's chip.
        handle = self._leaser.acquire(
            self.devices_per_replica,
            label=f"serve@{self.name}:r{idx}",
            timeout=self.lease_timeout_s,
        )
        replica = Replica(self.name, idx, handle)
        replica.batcher = MicroBatcher(
            self._factory(replica),
            max_batch=self._cfg.max_batch,
            max_queue=self._cfg.max_queue,
            flush_ms=self._cfg.flush_ms,
            name=f"{self.name}:r{idx}",
        )
        if self._warmup is not None:
            # Warm BEFORE the replica is routable: the dummy
            # dispatches pay XLA's per-device executable load here,
            # not under the first routed request's latency.  A failed
            # warm-up is logged and the replica serves cold (warmed
            # stays False) — availability beats warmth.
            try:
                with tracing.span(
                    "replica.warmup", model=self.name, replica=idx,
                    device=replica.device_id or "host",
                ):
                    self._warmup(replica)
                replica.warmed = True
            except Exception as exc:  # noqa: BLE001
                logger.warning(kv(
                    event="replica_warmup_failed", model=self.name,
                    replica=idx,
                    device=replica.device_id or "host",
                    error=repr(exc),
                ))
        with self._lock:
            # Closed (or raced past max by a concurrent scaler) while
            # the lease was being placed: hand everything straight back.
            discard = (
                self._closed
                or len(self._replicas) >= self.max_replicas
            )
            if not discard:
                self._replicas.append(replica)
                self.scale_ups += 1
        if discard:
            replica.batcher.close()
            replica.release()
            return False
        logger.info(kv(
            event="replica_up", model=self.name, replica=idx,
            device=replica.device_id or "host", reason=reason,
        ))
        return True

    def _remove_replica(self, reason: str) -> None:
        with self._lock:
            if len(self._replicas) <= 1:
                return  # never drain the last routable replica
            # Newest-first keeps replica 0 (the longest-warm one)
            # stable across scale cycles.
            victim = self._replicas.pop()
            self.scale_downs += 1
        # Counters move to _retired BEFORE the (up to 30 s) drain: a
        # scrape during the drain window must not see the victim's
        # lifetime totals in neither the live list nor the retired
        # pool — that transient dip would read as a Prometheus counter
        # reset and feed the autoscaler spurious negative deltas.
        pre = victim.batcher.stats()
        self.absorb_stats(pre)
        # Drain OUTSIDE the lock: close() flushes everything already
        # queued (requests keep completing), new submits re-route.
        victim.batcher.close(join=False)
        self._retire(victim, reason, pre)

    def _retire(self, victim: Replica, reason: str,
                pre: dict | None = None) -> None:
        """Post-close teardown: fold in final counters and return the
        chip — but ONLY once the batcher worker has really exited.  A
        join that timed out behind a wedged dispatch means the device
        is still in use; releasing it would double-book the chip with
        the next lessee, so the lease is deliberately retained (and
        logged) instead.  ``pre`` is the stats snapshot already
        absorbed at pop time; only the drain's delta is added here."""
        drained = victim.batcher.wait_drained(timeout=30)
        final = victim.batcher.stats()
        self.absorb_stats(_stats_delta(final, pre) if pre else final)
        if drained:
            victim.release()
            logger.info(kv(
                event="replica_down", model=self.name,
                replica=victim.idx,
                device=victim.device_id or "host", reason=reason,
            ))
        else:
            logger.warning(kv(
                event="replica_down_undrained", model=self.name,
                replica=victim.idx,
                device=victim.device_id or "host", reason=reason,
                note="worker still dispatching; lease retained",
            ))

    def _absorb_retired(self, batcher: MicroBatcher) -> None:
        self.absorb_stats(batcher.stats())

    def absorb_stats(self, stats: dict, *,
                     overflows_were_sheds: bool = False) -> None:
        """Fold another batcher's lifetime counters into this set's
        retired totals: drained replicas at scale-down, and the
        single-path batcher a model retires when it moves onto the
        fleet — per-model counters stay monotonic across both.

        ``overflows_were_sheds``: on the SINGLE-path batcher every
        overflow was a client 429, so the cutover carries them into
        the set-level shed counter; a drained replica's overflows are
        not (those requests may have re-routed and served)."""
        with self._lock:
            retired = self._retired
            for key in _COUNTER_KEYS:
                retired[key] += stats[key]
            if overflows_were_sheds:
                self.sheds += stats["overflows"]
            retired["occ_weighted"] += (
                stats["batchOccupancy"] * stats["batches"]
            )
            for bucket, count in stats["bucketHistogram"].items():
                retired["buckets"][bucket] = (
                    retired["buckets"].get(bucket, 0) + count
                )

    # -- routing -------------------------------------------------------------

    def submit(self, x: np.ndarray) -> tuple:
        """Route one request: P2C on live queue depth, falling through
        the candidate order on per-replica overflow; raises
        ``QueueFull`` (→ 429 + Retry-After) only when EVERY replica
        refused.  Returns ``(outputs, replica)``."""
        with self._lock:
            replicas = list(self._replicas)
        if not replicas:
            raise BatcherClosed(
                f"no routable replicas for {self.name!r}; retry"
            )
        order = self.router.choose(
            [r.batcher.queue_depth for r in replicas]
        )
        last: QueueFull | None = None
        for i in order:
            replica = replicas[i]
            try:
                # Replica/device attribution on the serve span: a
                # single contextvar read when no trace is active.
                with tracing.span(
                    "serve.predict",
                    model=self.name, replica=replica.idx,
                    device=replica.device_id or "host",
                ):
                    return replica.batcher.submit(x), replica
            except BatcherClosed as exc:
                # Drained under us mid-route — not saturation; the
                # next candidate absorbs the request.
                last = exc
            except QueueFull as exc:
                last = exc
            if getattr(last, "partial", False):
                # Part of a chunked request already queued (and will
                # dispatch) on that replica: replaying the whole
                # request on another would DUPLICATE device work under
                # exactly the saturation that overflowed it — shed and
                # let the client's 429 backoff do its job.
                break
        with self._lock:
            self.sheds += 1
        raise last  # every replica saturated → shed (429)

    # -- signals / observability ---------------------------------------------

    def signals(self) -> dict:
        """The autoscaler's per-tick inputs — the same numbers the
        Prometheus exposition serves (queue depth, p99, cumulative
        requests and 429 overflows), read from the batchers' own
        counters.  Batch occupancy is deliberately NOT here: with
        power-of-two bucket padding a lone request dispatches at
        occupancy 1.0 (bucket 1), so occupancy stays high at trickle
        load and cannot distinguish a busy fleet from an idle one —
        it remains an operator metric (merged_stats), not a scale
        signal."""
        with self._lock:
            replicas = list(self._replicas)
            requests = self._retired["requests"]
            sheds = self.sheds
        depth = 0
        p99 = 0.0
        for r in replicas:
            stats = r.batcher.stats()
            depth += stats["queueDepth"]
            requests += stats["requests"]
            p99 = max(p99, stats["latencyMs"]["p99"])
        n = len(replicas)
        cap = max(1, n * self._cfg.max_queue)
        return {
            "replicas": n,
            "queue_depth": depth,
            "queue_frac": depth / cap,
            "p99_ms": p99,
            # Set-level: only requests EVERY candidate refused (real
            # 429s), not per-replica overflows that re-routed fine.
            "sheds": sheds,
            "requests": requests,
        }

    def merged_stats(self) -> dict:
        """Replica batcher stats merged into the single-batcher shape
        ``ServingService.aggregate`` consumes, so fleet models land on
        every existing surface (tfevents, /metrics.prom, monitoring)
        without a second aggregation path."""
        with self._lock:
            replicas = list(self._replicas)
            retired = {
                key: (dict(val) if isinstance(val, dict) else val)
                for key, val in self._retired.items()
            }
            sheds = self.sheds
        merged = {
            "requests": retired["requests"], "rows": retired["rows"],
            "batches": retired["batches"],
            "paddedRows": retired["paddedRows"],
            # Client-visible 429s only: per-replica overflows that
            # re-routed and SERVED are a routing detail, and the
            # serving_overflows surfaces have always meant "requests
            # answered 429".
            "overflows": sheds, "queueDepth": 0,
            "maxBatch": self._cfg.max_batch,
            "maxQueue": self._cfg.max_queue,
            "flushMs": self._cfg.flush_ms,
            "replicas": len(replicas),
        }
        occ_weighted = retired["occ_weighted"]
        buckets: dict[str, int] = retired["buckets"]
        lat = {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        for r in replicas:
            stats = r.batcher.stats()
            for key in ("requests", "rows", "batches", "paddedRows",
                        "queueDepth"):
                merged[key] += stats[key]
            occ_weighted += stats["batchOccupancy"] * stats["batches"]
            for b, count in stats["bucketHistogram"].items():
                buckets[b] = buckets.get(b, 0) + count
            for q in lat:
                lat[q] = max(lat[q], stats["latencyMs"][q])
        merged["batchOccupancy"] = round(
            occ_weighted / merged["batches"], 4
        ) if merged["batches"] else 0.0
        merged["bucketHistogram"] = dict(sorted(buckets.items()))
        merged["latencyMs"] = lat
        return merged

    def placements(self) -> dict:
        with self._lock:
            return {
                r.idx: (r.device_id or "host") for r in self._replicas
            }

    def status(self) -> dict:
        with self._lock:
            replicas = list(self._replicas)
        return {
            "model": self.name,
            "replicas": [r.status() for r in replicas],
            "size": len(replicas),
            "min": self.min_replicas,
            "max": self.max_replicas,
            "devicesPerReplica": self.devices_per_replica,
            "scaleUps": self.scale_ups,
            "scaleDowns": self.scale_downs,
        }

    def close(self) -> None:
        """Tear the whole set down (unload/invalidation/shutdown):
        drain every batcher, release every chip."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            replicas = self._replicas
            self._replicas = []
        # Signal every batcher first so the drains overlap, then wait
        # and release — serial close-then-join would stack each
        # replica's drain timeout on shutdown's critical path.
        pres = []
        for r in replicas:
            pres.append(r.batcher.stats())
            self.absorb_stats(pres[-1])
            r.batcher.close(join=False)
        for r, pre in zip(replicas, pres):
            self._retire(r, "close", pre)
