"""Metrics-driven replica autoscaling — the 429's remediation path.

The serving tier already EXPORTS the saturation story (batcher queue
depth, p99 latency, request and 429 overflow counts — the
``lo_serving_*`` families on ``/metrics.prom``); until now nothing
consumed it.  This control loop reads those signals — straight from
the batchers' own counters, the same source the exposition renders —
and turns sustained pressure into replicas instead of refusals.
(Batch occupancy stays an operator metric only: bucket padding keeps
it near 1.0 even at trickle load, so it cannot separate busy from
idle — see ``ReplicaSet.signals``.)  The decisions:

- **scale up** when the fleet-wide queue fraction holds above
  ``LO_TPU_FLEET_UP_QUEUE_FRAC`` for ``LO_TPU_FLEET_UP_TICKS``
  consecutive ticks, when requests were SHED (any new 429 overflow is
  by definition saturation), when p99 latency crosses
  ``LO_TPU_FLEET_UP_P99_MS`` (optional), or — optionally — when the
  model's queue depth GROWS faster than ``LO_TPU_FLEET_UP_SLOPE``
  rows/second, least-squares-fitted over the shared rollup series
  (``lo_serving_model_queue_depth``, obs/rollup.py) so a ramp scales
  BEFORE the level crosses the queue-frac threshold, or — cost-aware —
  when the model's DEVICE-TIME fraction since the last tick (decode
  steps + serving dispatches, the obs/costs attribution ledger)
  crosses ``LO_TPU_FLEET_UP_DEVICE_FRAC`` (compute-bound decode keeps
  queues short while pinning the chip; queue depth alone cannot see
  that saturation);
- **scale down** after ``LO_TPU_FLEET_DOWN_TICKS`` consecutive
  empty-queue ticks, draining the victim's batcher before its chip
  lease returns to the pool (training jobs queued on the leaser get
  the chip back).

Sustain counts (not instantaneous thresholds) are the hysteresis: one
bursty tick must not thrash a replica up and down, and the counts make
drills deterministic — k ticks of injected delay scale at exactly tick
k.  Decisions are bounded per tick (±1 replica per model) so a signal
spike converges gradually instead of slamming the lease pool.

The loop is a daemon thread owned by the FleetManager, started only
when some model can actually scale (max > 1) — a default deployment
pays nothing.  ``tick()`` is public and thread-safe so tests drive the
schedule deterministically without the thread.
"""

from __future__ import annotations

import collections
import threading
import time

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.jobs.leases import LeaseTimeout
from learningorchestra_tpu.log import get_logger, kv

logger = get_logger("fleet")


class Autoscaler:
    """Per-tick scale decisions over a FleetManager's replica sets."""

    def __init__(self, manager, cfg):
        self._manager = manager
        self.cfg = cfg
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = make_lock("Autoscaler._lock")
        # model -> {"up": streak, "down": streak, "overflows": last}
        self._state: dict[str, dict] = {}
        self.ticks = 0
        self.decisions: collections.deque = collections.deque(maxlen=64)
        # Decision LEDGER: every per-model evaluation — scale, hold,
        # blocked — with the signal values and sustain counters it
        # read (queue-frac, shed, p99).  ``decisions`` above keeps
        # only the scale events; drills could see THAT the fleet
        # moved but never WHY it held, so the ledger records the
        # holds too.  Bounded ring; served under GET /serve/fleet.
        self.ledger: collections.deque = collections.deque(maxlen=256)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None or self.cfg.interval_s <= 0:
                return
            self._thread = threading.Thread(
                target=self._run, name="fleet-autoscaler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                # any one tick's failure; a dead autoscaler is a fleet
                # silently frozen at its current size.
                logger.exception("autoscaler tick failed")

    # -- the control loop body -----------------------------------------------

    def tick(self) -> list[dict]:
        """One pass over every replica set; returns the decisions made
        (also appended to the rolling ``decisions`` history)."""
        made: list[dict] = []
        with self._lock:
            self.ticks += 1
        for name, rs in self._manager.sets_snapshot():
            sig = rs.signals()
            slope = self._queue_slope(name)
            dev_s = self._device_seconds(name)
            now_mono = time.monotonic()
            with self._lock:
                st = self._state.setdefault(
                    name, {"up": 0, "down": 0,
                           "sheds": sig["sheds"],
                           "requests": sig["requests"],
                           "dev_s": dev_s, "dev_t": now_mono}
                )
                shed = sig["sheds"] - st["sheds"]
                st["sheds"] = sig["sheds"]
                served = sig["requests"] - st.get(
                    "requests", sig["requests"]
                )
                st["requests"] = sig["requests"]
                # Cost-aware trigger: fraction of wall time this
                # model spent ON DEVICE since the last tick (decode
                # steps + serving dispatches, the obs/costs devtime
                # ledger).  Near 1.0 means the replica's chip is
                # compute-bound even if its queue drains between
                # ticks — the saturation queue depth cannot see.
                dt = now_mono - st.get("dev_t", now_mono)
                device_frac = (
                    (dev_s - st.get("dev_s", dev_s)) / dt
                    if dt > 0 else 0.0
                )
                st["dev_s"] = dev_s
                st["dev_t"] = now_mono
                dev_sig = (
                    self.cfg.up_device_frac > 0
                    and device_frac >= self.cfg.up_device_frac
                )
                # Growth-slope trigger: the queue is RAMPING even if
                # its level is still under the frac threshold — the
                # rate-of-change controller the decision ledger's
                # signal history was recorded to justify.  Gated on
                # traffic this tick like p99 (a stale rollup window
                # must not scale an idle fleet).
                slope_sig = (
                    self.cfg.up_slope > 0 and slope is not None
                    and served > 0
                    and slope >= self.cfg.up_slope
                )
                up_sig = (
                    sig["queue_frac"] >= self.cfg.up_queue_frac
                    or shed > 0
                    # p99 comes from the batchers' rolling latency
                    # window, which FREEZES when traffic stops — gate
                    # it on traffic this tick, or a stale high p99
                    # would hold an idle fleet at max forever.
                    or (self.cfg.up_p99_ms > 0 and served > 0
                        and sig["p99_ms"] >= self.cfg.up_p99_ms)
                    or slope_sig
                    or dev_sig
                )
                # "Idle" means NO traffic since the last tick, not an
                # instantaneously empty queue: under steady load the
                # batchers flush between ticks and queue_depth samples
                # 0, and scaling down on that would drop a loaded
                # fleet to min, shed 429s for an up-sustain window,
                # scale back up, and oscillate.
                down_sig = (
                    sig["queue_depth"] == 0 and shed == 0
                    and served == 0
                )
                n = sig["replicas"]
                target, reason = n, ""
                # A recent LeaseTimeout means the chip pool is
                # saturated: skip further scale-UP attempts for this
                # model until the block expires — each attempt costs
                # a full lease_timeout_s inside the tick, and a tick
                # wedged in doomed waits delays every OTHER model's
                # decisions (including the scale-downs that would
                # free the very chips being waited on).
                blocked = time.monotonic() < st.get(
                    "blocked_until", 0.0
                )
                if n < rs.min_replicas:
                    # Below min (a partially-placed ensure whose later
                    # leases timed out): heal toward min immediately —
                    # no sustain window, this is repair, not reaction.
                    if not blocked:
                        target, reason = n + 1, "min"
                elif up_sig and n < rs.max_replicas:
                    st["down"] = 0
                    st["up"] += 1
                    if st["up"] >= self.cfg.up_ticks and not blocked:
                        # The ledger must show the streak that
                        # TRIGGERED the move, not the post-reset 0.
                        triggered = st["up"]
                        st["up"] = 0
                        target = n + 1
                        reason = (
                            "shed" if shed > 0 else
                            "queue" if sig["queue_frac"]
                            >= self.cfg.up_queue_frac else
                            "p99" if (
                                self.cfg.up_p99_ms > 0
                                and sig["p99_ms"]
                                >= self.cfg.up_p99_ms
                            ) else
                            "slope" if slope_sig else "devtime"
                        )
                elif down_sig and n > rs.min_replicas:
                    st["up"] = 0
                    st["down"] += 1
                    if st["down"] >= self.cfg.down_ticks:
                        triggered = st["down"]
                        st["down"] = 0
                        target = n - 1
                        reason = "idle"
                else:
                    st["up"] = st["up"] if up_sig else 0
                    st["down"] = st["down"] if down_sig else 0
                up_streak, down_streak = st["up"], st["down"]
                if target > n and reason != "min":
                    up_streak = triggered
                elif target < n:
                    down_streak = triggered
            # Ledger entry for EVERY evaluation — the holds included:
            # a drill reading GET /serve/fleet can see exactly which
            # signal values and sustain counters produced (or
            # withheld) each move.
            record = {
                "t": time.time(),
                "tick": self.ticks,
                "model": name,
                "replicas": n,
                "queueFrac": round(sig["queue_frac"], 4),
                "shed": shed,
                "served": served,
                "p99Ms": sig["p99_ms"],
                # Queue-depth growth rate (rows/s) from the shared
                # rollup series; None while the rollup engine has too
                # few points (or is disabled) to fit one.
                "queueSlope": (
                    round(slope, 4) if slope is not None else None
                ),
                # Device-time fraction since the last tick (decode +
                # predict attribution) — the cost-aware signal; 0.0
                # on a model's first evaluation.
                "deviceFrac": round(device_frac, 4),
                "upStreak": up_streak,
                "downStreak": down_streak,
                "blocked": blocked,
                "action": "hold" if target == n
                else ("up" if target > n else "down"),
                "reason": reason or "hold",
            }
            if target == n:
                with self._lock:
                    self.ledger.append(record)
                continue
            try:
                result = self._manager.scale(
                    name, target, reason=f"auto:{reason}"
                )
            except LeaseTimeout:
                # Chip pool saturated: note it and re-arm the streak so
                # the next tick retries immediately instead of waiting
                # out a fresh sustain window.  (.get: the model may
                # have been dropped — forget() — while the lease
                # attempt blocked.)
                with self._lock:
                    st = self._state.get(name)
                    if st is not None:
                        st["up"] = self.cfg.up_ticks
                        st["blocked_until"] = (
                            time.monotonic()
                            + self.cfg.lease_timeout_s
                        )
                logger.warning(kv(
                    event="scale_up_blocked", model=name,
                    wanted=target, reason="lease_timeout",
                ))
                record["action"] = "blocked"
                record["reason"] = "lease_timeout"
                record["wanted"] = target
                with self._lock:
                    self.ledger.append(record)
                continue
            decision = {
                "t": time.time(),
                "model": name,
                "from": n,
                "to": result,
                "signal": reason,
                "queueFrac": round(sig["queue_frac"], 4),
                "shed": shed,
                "p99Ms": sig["p99_ms"],
            }
            record["to"] = result
            with self._lock:
                self.decisions.append(decision)
                self.ledger.append(record)
            made.append(decision)
        return made

    def _queue_slope(self, name: str) -> float | None:
        """This model's queue-depth growth rate (rows/second) from the
        SHARED rollup series — the same windowed view the timeseries
        endpoint serves, not a private re-sample.  ``None`` when the
        rollup engine is disabled, hasn't two points yet, or the
        query fails (the autoscaler must never die on an obs hiccup)."""
        try:
            from learningorchestra_tpu.obs.rollup import get_engine

            return get_engine().slope(
                "lo_serving_model_queue_depth", {"model": name},
                self.cfg.slope_window_s,
            )
        except Exception:  # noqa: BLE001
            return None

    def _device_seconds(self, name: str) -> float:
        """This model's accumulated device-seconds from the obs/costs
        attribution ledger (decode steps + serving dispatches).  0.0
        when cost tracking is disabled or errors — the autoscaler
        must never die on an obs hiccup."""
        try:
            from learningorchestra_tpu.obs import costs as obs_costs

            return obs_costs.devtime().model_device_s(name)
        except Exception:  # noqa: BLE001
            return 0.0

    def forget(self, name: str) -> None:
        """Drop a dissolved model's streak state (manager drop path)."""
        with self._lock:
            self._state.pop(name, None)

    def status(self) -> dict:
        with self._lock:
            return {
                "running": self._thread is not None
                and self._thread.is_alive(),
                "intervalS": self.cfg.interval_s,
                "upQueueFrac": self.cfg.up_queue_frac,
                "upTicks": self.cfg.up_ticks,
                "downTicks": self.cfg.down_ticks,
                "upP99Ms": self.cfg.up_p99_ms,
                "upSlope": self.cfg.up_slope,
                "slopeWindowS": self.cfg.slope_window_s,
                "upDeviceFrac": self.cfg.up_device_frac,
                "ticks": self.ticks,
                "streaks": {
                    name: {"up": st["up"], "down": st["down"]}
                    for name, st in self._state.items()
                },
                "decisions": list(self.decisions),
                # The full per-evaluation ledger (holds included) —
                # why the fleet moved, or didn't, each tick.
                "ledger": list(self.ledger),
            }
