"""MicroBatcher — request coalescing into padded bucket dispatches.

One accelerator dispatch amortizes over every request in flight: N
concurrent single-row predicts cost one padded bucket-sized ``apply``
instead of N row-sized ones (dispatch overhead dominates small-batch
inference; on a remote-TPU link one round-trip is ~7 ms+).  The policy
is the standard serving pair:

- **max batch**: a dispatch fires as soon as ``max_batch`` rows are
  waiting (never exceeded — oversized requests are chunked at submit);
- **flush deadline**: otherwise it fires ``flush_ms`` after the OLDEST
  waiting request arrived — the latency bound a lone request pays.

Backpressure is a bounded row queue: ``submit`` raises
:class:`QueueFull` instead of queueing unboundedly (the API layer maps
it to 429 + Retry-After).  Observability: rolling p50/p95/p99 request
latency, queue depth, mean batch occupancy and a bucket histogram.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable

import numpy as np

from learningorchestra_tpu.concurrency_rt import make_condition
from learningorchestra_tpu.serve.bucketing import bucket_for, pad_rows


class QueueFull(Exception):
    """Bounded request queue is at capacity — shed load (429)."""


class BatcherClosed(QueueFull):
    """Batcher torn down (unload/invalidation/shutdown) while the
    request was arriving.  A QueueFull subtype on purpose: the API
    layer's 429 + Retry-After path absorbs it, and the client's retry
    lands on a freshly-created batcher (or a clean 404 if the model is
    really gone) — a transient teardown must never surface as a 500."""


class _Pending:
    __slots__ = ("x", "event", "result", "error", "t_enqueue")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.t_enqueue = time.monotonic()


#: Rolling latency window for the percentile stats.
_LATENCY_WINDOW = 2048


class MicroBatcher:
    """Coalesce concurrent ``submit`` calls into bucket dispatches.

    ``dispatch`` receives one host array already padded to a bucket
    (``shape[0]`` IS the bucket) and returns the model outputs for it;
    the batcher slices off pad rows and splits results per request.
    """

    def __init__(
        self,
        dispatch: Callable[[np.ndarray], np.ndarray],
        *,
        max_batch: int = 64,
        max_queue: int = 256,
        flush_ms: float = 5.0,
        name: str = "",
    ):
        if max_batch <= 0:
            raise ValueError(f"max_batch must be positive, got {max_batch}")
        self._dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.flush_s = max(0.0, float(flush_ms)) / 1e3
        self.name = name
        self._queue: collections.deque[_Pending] = collections.deque()
        self._rows_queued = 0
        self._cond = make_condition("MicroBatcher._cond")
        self._closed = False
        # Counters (lifetime) + rolling latency window.
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.padded_rows = 0
        self.overflows = 0
        self.bucket_counts: dict[int, int] = {}
        self._occupancy_sum = 0.0
        self._latencies: collections.deque[float] = collections.deque(
            maxlen=_LATENCY_WINDOW
        )
        self._worker = threading.Thread(
            target=self._run, name=f"microbatch-{name or 'serve'}",
            daemon=True,
        )
        self._worker.start()

    # -- submit side ---------------------------------------------------------

    def submit(self, x: np.ndarray) -> np.ndarray:
        """Enqueue ``x`` (rows on axis 0), block until its outputs are
        ready.  Raises :class:`QueueFull` under backpressure; re-raises
        the dispatch's exception on model failure.

        Oversized requests chunk to ``max_batch`` and enqueue ALL
        chunks before waiting, so a big request's pieces ride
        concurrent dispatches instead of serializing.  (A mid-request
        QueueFull abandons the already-queued chunks' results — the
        caller retries the whole request, the standard 429 contract.)
        """
        x = np.asarray(x)
        if x.ndim == 0 or x.shape[0] == 0:
            raise ValueError("submit needs at least one row")
        pendings: list[_Pending] = []
        try:
            for i in range(0, x.shape[0], self.max_batch):
                pendings.append(self._enqueue(x[i:i + self.max_batch]))
        except QueueFull as exc:
            if pendings:
                # Earlier chunks already queued and WILL dispatch
                # (results abandoned).  The flag tells a routing
                # layer not to replay the whole request elsewhere —
                # that would duplicate this batcher's device work
                # under exactly the saturation that caused the
                # overflow.
                exc.partial = True
            raise
        outs = []
        for p in pendings:
            p.event.wait()
            if p.error is not None:
                raise p.error
            outs.append(p.result)
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def _enqueue(self, x: np.ndarray) -> _Pending:
        pending = _Pending(x)
        with self._cond:
            if self._closed:
                raise BatcherClosed(
                    f"batcher {self.name!r} is closed; retry"
                )
            if self._rows_queued + x.shape[0] > self.max_queue:
                self.overflows += 1
                raise QueueFull(
                    f"serving queue full ({self._rows_queued} rows "
                    f"queued, cap {self.max_queue})"
                )
            self._queue.append(pending)
            self._rows_queued += x.shape[0]
            self.requests += 1
            self._cond.notify_all()
        return pending

    # -- worker side ---------------------------------------------------------

    def _take_batch_locked(self) -> list[_Pending]:
        batch, rows = [], 0
        while self._queue and (
            rows + self._queue[0].x.shape[0] <= self.max_batch
        ):
            p = self._queue.popleft()
            rows += p.x.shape[0]
            batch.append(p)
        self._rows_queued -= rows
        return batch

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue and self._closed:
                    return
                # Coalesce until max_batch rows OR the oldest request's
                # flush deadline, whichever comes first.  close() flushes
                # immediately so shutdown never strands waiters.
                deadline = self._queue[0].t_enqueue + self.flush_s
                while (
                    self._rows_queued < self.max_batch
                    and not self._closed
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                batch = self._take_batch_locked()
            if batch:
                self._run_batch(batch)

    def _run_batch(self, batch: list[_Pending]) -> None:
        xs = (
            batch[0].x if len(batch) == 1
            else np.concatenate([p.x for p in batch], axis=0)
        )
        n = xs.shape[0]
        bucket = bucket_for(n, self.max_batch)
        try:
            out = np.asarray(self._dispatch(pad_rows(xs, bucket)))[:n]
        except Exception as exc:  # noqa: BLE001 — fail the REQUESTS,
            # never the worker (one bad model call must not kill the
            # batcher for every later request).
            for p in batch:
                p.error = exc
                p.event.set()
            return
        done = time.monotonic()
        with self._cond:
            self.batches += 1
            self.rows += n
            self.padded_rows += bucket - n
            self.bucket_counts[bucket] = (
                self.bucket_counts.get(bucket, 0) + 1
            )
            self._occupancy_sum += n / bucket
            for p in batch:
                self._latencies.append(done - p.t_enqueue)
        offset = 0
        for p in batch:
            k = p.x.shape[0]
            p.result = out[offset:offset + k]
            offset += k
            p.event.set()

    # -- observability / lifecycle -------------------------------------------

    @property
    def queue_depth(self) -> int:
        """Racy snapshot of queued rows — the fleet router's live load
        signal, read per routing decision.  A plain int read under the
        GIL: balancing needs freshness, not exactness, and taking the
        condition lock here would serialize every router pick against
        every submit."""
        return self._rows_queued

    def stats(self) -> dict:
        with self._cond:
            lat = sorted(self._latencies)
            occupancy = (
                self._occupancy_sum / self.batches if self.batches else 0.0
            )

            def pct(q: float) -> float:
                if not lat:
                    return 0.0
                idx = min(len(lat) - 1, int(q * (len(lat) - 1)))
                return round(lat[idx] * 1e3, 3)

            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "paddedRows": self.padded_rows,
                "overflows": self.overflows,
                "queueDepth": self._rows_queued,
                "maxBatch": self.max_batch,
                "maxQueue": self.max_queue,
                "flushMs": round(self.flush_s * 1e3, 3),
                "batchOccupancy": round(occupancy, 4),
                "bucketHistogram": {
                    str(k): v
                    for k, v in sorted(self.bucket_counts.items())
                },
                "latencyMs": {
                    "p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
                },
            }

    def close(self, join: bool = True) -> None:
        """Stop accepting work, flush what's queued, join the worker.
        ``join=False`` only signals — callers closing MANY batchers
        (fleet teardown) signal them all first so the drains overlap,
        then wait via :meth:`wait_drained`."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if join:
            self._worker.join(timeout=30)

    def wait_drained(self, timeout: float | None = None) -> bool:
        """True once the worker thread has actually exited (close()'s
        join can time out behind a slow backlog).  The fleet's
        drain-before-lease-return gate: a chip must not go back to the
        pool while this batcher could still be dispatching on it."""
        self._worker.join(timeout)
        return not self._worker.is_alive()
