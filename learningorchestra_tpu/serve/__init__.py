"""Resident model serving — the online-inference subsystem.

The reference (and this framework through PR 1) only exposes predict as
an asynchronous persisted JOB: submit, poll, read result rows — fine
for batch analytics, hopeless for online traffic where every request
would pay job dispatch plus two store round-trips.  This package turns
the framework into an inference server:

- :mod:`bucketing` — power-of-two shape buckets and row padding, shared
  with ``NeuralEstimator.predict`` (one compiled shape per bucket);
- :mod:`registry` — ``ModelRegistry``: trained artifacts' params pinned
  resident on device, LRU with a byte cap, invalidated when the backing
  artifact is overwritten or deleted;
- :mod:`batcher` — ``MicroBatcher``: concurrent predict requests
  coalesce into one padded bucket-shaped dispatch (max-batch or
  flush-deadline, whichever first), with a bounded queue for
  backpressure and latency/occupancy stats;
- :mod:`service` — ``ServingService``: the REST-facing facade
  (load/unload/list/predict + observability);
- :mod:`fleet` — the multi-replica data plane: per-replica chip leases
  + MicroBatchers, power-of-two-choices routing on live queue depth,
  and the metrics-driven autoscaler (``LO_TPU_FLEET_*``).

Sizing knobs live in config.py (``LO_TPU_SERVE_*``).
"""

from learningorchestra_tpu.serve.batcher import MicroBatcher, QueueFull
from learningorchestra_tpu.serve.bucketing import (
    bucket_for,
    bucket_sizes,
    pad_rows,
)
from learningorchestra_tpu.serve.fleet import (
    Autoscaler,
    FleetManager,
    P2CRouter,
    ReplicaSet,
)
from learningorchestra_tpu.serve.registry import ModelRegistry
from learningorchestra_tpu.serve.service import ServingService

__all__ = [
    "Autoscaler",
    "FleetManager",
    "MicroBatcher",
    "ModelRegistry",
    "P2CRouter",
    "QueueFull",
    "ReplicaSet",
    "ServingService",
    "bucket_for",
    "bucket_sizes",
    "pad_rows",
]
