"""End-to-end job tracing: request IDs and named spans.

Answers "where did this job's 40 seconds go?": a request ID is minted
at the API layer (or taken from the client's ``X-Request-Id`` header,
and echoed on every response), propagated through the job engine into
the worker thread that runs the job, and every interesting interval on
the way — queue wait, chip-lease hold, program compile, per-epoch
steps — is recorded as a named span with start/end/attrs.  On job
completion the span list persists into the artifact's execution ledger
(store/artifacts.py), where ``GET /observability/jobs/<name>/trace``
serves it back as a span tree.

Propagation model: context variables carry (request id, active trace,
current span id) per thread.  The job engine explicitly re-activates
the submitting request's trace inside its worker thread — thread pools
do not inherit context — so spans recorded anywhere down the call
stack (leases, compile cache, the train loop) attach to the right job
with the right parent without any of those layers knowing about HTTP.

Span timestamps anchor to ONE (wall, monotonic) pair captured at trace
creation: durations are monotonic-accurate, wall times are readable.

Everything here is a no-op when the registry is disabled
(``LO_TPU_OBS_ENABLED=0``) or tracing is off (``LO_TPU_OBS_TRACE=0``);
the fast path out is a single context-variable read.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
import uuid

from learningorchestra_tpu.concurrency_rt import make_lock

__all__ = [
    "JobTrace",
    "current_trace",
    "get_request_id",
    "new_request_id",
    "new_trace",
    "record_span",
    "set_request_id",
    "reset_request_id",
    "sampled",
    "span",
    "span_tree",
    "activate",
]

_REQUEST_ID: contextvars.ContextVar = contextvars.ContextVar(
    "lo_request_id", default=None
)
_TRACE: contextvars.ContextVar = contextvars.ContextVar(
    "lo_trace", default=None
)
_SPAN: contextvars.ContextVar = contextvars.ContextVar(
    "lo_span", default=None
)


# -- request ids --------------------------------------------------------------


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def set_request_id(request_id: str | None):
    """Bind the calling thread's current request id; returns the token
    for :func:`reset_request_id`."""
    return _REQUEST_ID.set(request_id)


def reset_request_id(token) -> None:
    _REQUEST_ID.reset(token)


def get_request_id() -> str | None:
    return _REQUEST_ID.get()


# -- traces and spans ---------------------------------------------------------


class JobTrace:
    """Span accumulator for one job.  Thread-safe: the engine worker,
    the train loop and (via the compile cache) coalesced builders may
    all record into it."""

    def __init__(self, job: str, request_id: str | None = None,
                 max_spans: int = 512):
        self.job = job
        self.request_id = request_id
        self.max_spans = int(max_spans)
        self._lock = make_lock("JobTrace._lock")
        self._spans: dict[int, dict] = {}
        self._next_id = 1
        self.dropped = 0
        # One (wall, monotonic) anchor: every span's monotonic stamps
        # convert to wall time through it, so durations stay immune to
        # wall-clock jumps while start/end remain human-readable.
        self._wall0 = time.time()
        self._mono0 = time.monotonic()

    def _wall(self, mono: float) -> float:
        return self._wall0 + (mono - self._mono0)

    def begin(self, name: str, parent: int | None = None,
              attrs: dict | None = None) -> int:
        """Open a span; returns its id, or -1 past the span cap (the
        caller then skips the matching :meth:`end`)."""
        t0 = time.monotonic()
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                return -1
            sid = self._next_id
            self._next_id += 1
            self._spans[sid] = {
                "id": sid,
                "parent": parent,
                "name": name,
                "start": round(self._wall(t0), 6),
                "end": None,
                "durationS": None,
                "attrs": dict(attrs or {}),
                "_t0": t0,
            }
            return sid

    def end(self, sid: int) -> None:
        if sid < 0:
            return
        t1 = time.monotonic()
        with self._lock:
            rec = self._spans.get(sid)
            if rec is None or rec["end"] is not None:
                return
            rec["end"] = round(self._wall(t1), 6)
            rec["durationS"] = round(t1 - rec["_t0"], 6)

    def add_span(self, name: str, t0: float, t1: float,
                 parent: int | None = None,
                 attrs: dict | None = None) -> int:
        """Record an already-elapsed interval (monotonic stamps)."""
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                return -1
            sid = self._next_id
            self._next_id += 1
            self._spans[sid] = {
                "id": sid,
                "parent": parent,
                "name": name,
                "start": round(self._wall(t0), 6),
                "end": round(self._wall(t1), 6),
                "durationS": round(t1 - t0, 6),
                "attrs": dict(attrs or {}),
                "_t0": t0,
            }
            return sid

    def to_doc(self) -> dict:
        """JSON-safe record for the execution ledger.  Unfinished
        spans (a crash mid-interval) keep ``end: None`` — visibly
        open, never fabricated."""
        with self._lock:
            spans = [
                {k: v for k, v in rec.items() if not k.startswith("_")}
                for _sid, rec in sorted(self._spans.items())
            ]
        return {
            "requestId": self.request_id,
            "job": self.job,
            "spans": spans,
            "droppedSpans": self.dropped,
        }


def sampled(basis: str, fraction: float) -> bool:
    """Deterministic sampling decision for ``basis`` (a request id, or
    the job name when the submission carried none): a retried request
    samples the SAME way, so a drill re-running one request id either
    always has its span tree or never does — no flaky traces."""
    if fraction >= 1.0:
        return True
    if fraction <= 0.0:
        return False
    import zlib

    return (zlib.crc32(basis.encode()) % 10_000) < fraction * 10_000


def new_trace(job: str, request_id: str | None = None) -> JobTrace | None:
    """A JobTrace sized from config, or None when tracing is off or
    the LO_TPU_OBS_TRACE_SAMPLE decision excluded this job — callers
    guard every later touch on that None (a sampled-out job keeps all
    its metrics; only the persisted span tree is skipped)."""
    from learningorchestra_tpu.obs.metrics import get_registry

    registry = get_registry()
    if not registry.trace_enabled:
        return None
    if not sampled(request_id or job,
                   getattr(registry, "trace_sample", 1.0)):
        return None
    return JobTrace(job, request_id, max_spans=registry.max_spans)


def current_trace() -> JobTrace | None:
    return _TRACE.get()


@contextlib.contextmanager
def activate(trace: JobTrace | None, root_span: int | None = None):
    """Bind ``trace`` (and optionally a current span) to the calling
    thread for the with-block — the engine's worker-thread handoff."""
    t_token = _TRACE.set(trace)
    s_token = _SPAN.set(root_span)
    r_token = (
        _REQUEST_ID.set(trace.request_id)
        if trace is not None and trace.request_id else None
    )
    try:
        yield trace
    finally:
        _TRACE.reset(t_token)
        _SPAN.reset(s_token)
        if r_token is not None:
            _REQUEST_ID.reset(r_token)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Record the with-block as a named span on the current trace (a
    no-op when none is active).  Spans opened inside nest under it."""
    trace = _TRACE.get()
    if trace is None:
        yield None
        return
    sid = trace.begin(name, parent=_SPAN.get(), attrs=attrs)
    token = _SPAN.set(sid) if sid >= 0 else None
    try:
        yield sid
    finally:
        if token is not None:
            _SPAN.reset(token)
        trace.end(sid)


def record_span(name: str, duration_s: float, **attrs) -> None:
    """Record an interval that just ended (duration known, end = now)
    on the current trace — the cheap form for per-epoch loops that
    already time themselves."""
    trace = _TRACE.get()
    if trace is None:
        return
    t1 = time.monotonic()
    trace.add_span(
        name, t1 - max(0.0, float(duration_s)), t1,
        parent=_SPAN.get(), attrs=attrs,
    )


def span_tree(spans: list[dict]) -> list[dict]:
    """Flat parent-linked span list → nested tree (children sorted by
    start time), the shape the trace endpoint serves."""
    nodes = {
        rec["id"]: {**rec, "children": []}
        for rec in spans
        if isinstance(rec.get("id"), int)
    }
    roots: list[dict] = []
    for rec in spans:
        node = nodes.get(rec.get("id"))
        if node is None:
            continue
        parent = nodes.get(rec.get("parent"))
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)

    def sort_rec(items: list[dict]) -> None:
        items.sort(key=lambda n: (n.get("start") or 0, n["id"]))
        for item in items:
            sort_rec(item["children"])

    sort_rec(roots)
    return roots
