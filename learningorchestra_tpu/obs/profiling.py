"""On-demand profiler capture: ``jax.profiler`` behind a REST surface.

The monitoring service already wraps distributed train jobs in
``jax.profiler.trace`` sessions, but nothing could capture a profile
from a LIVE process — the "serving p99 regressed in production, what

is the device doing right now?" workflow.  This module owns that:

- ``start(...)`` opens ONE capture at a time (a second start answers
  409 — jax's profiler is process-global) into a bounded capture
  directory, with an auto-stop deadline so a forgotten capture cannot
  trace forever and fill the disk;
- ``stop()`` ends it and records the capture's file manifest;
- ``list_captures()`` / ``read_file(...)`` serve listing + retrieval,
  so an operator pulls the ``.xplane.pb`` artifacts over HTTP and
  loads them into TensorBoard's profile plugin offline.

Knobs (``LO_TPU_PROF_*``, config.py ProfilingConfig): capture dir,
auto-stop seconds, retained-capture cap (oldest captures beyond it are
deleted on the next start — bounded disk, newest evidence wins).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time

from learningorchestra_tpu.concurrency_rt import make_lock

__all__ = [
    "ProfilerConflict",
    "ProfilerError",
    "ProfilerNotFound",
    "ProfilerService",
]

_NAME_RE = re.compile(r"[A-Za-z0-9_][A-Za-z0-9_.\-]*")
_META_FILE = "capture.json"


class ProfilerError(Exception):
    """Invalid profiler request (→ 406)."""


class ProfilerNotFound(Exception):
    """No such capture / capture file (→ 404)."""


class ProfilerConflict(Exception):
    """Capture state conflict: start while active, stop while idle
    (→ 409)."""


class ProfilerService:
    """Single-flight ``jax.profiler`` capture manager."""

    def __init__(self, root: str, *, max_seconds: float = 60.0,
                 max_captures: int = 8):
        self.root = str(root)
        self.max_seconds = float(max_seconds)
        self.max_captures = max(1, int(max_captures))
        self._lock = make_lock("ProfilerService._lock")
        self._active: dict | None = None
        # True while a stop's (potentially multi-second) trace flush
        # runs OUTSIDE the lock: a start arriving in that window
        # conflicts instead of racing start_trace against the
        # in-flight stop_trace.
        self._stopping = False
        self._deadline_timer: threading.Timer | None = None
        self.captures_total = 0
        self.auto_stops = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self, name: str | None = None,
              max_seconds: float | None = None) -> dict:
        """Begin a capture.  ``name`` defaults to a timestamp;
        ``max_seconds`` overrides the auto-stop deadline (clamped to
        the configured cap — a REST caller must not disable the bound
        that keeps a forgotten capture from tracing forever)."""
        if name is None:
            name = time.strftime("capture-%Y%m%d-%H%M%S")
            # Same-second restarts (drills) must not collide.
            with self._lock:
                name = f"{name}-{self.captures_total}"
        if not isinstance(name, str) or not _NAME_RE.fullmatch(name):
            raise ProfilerError(
                f"invalid capture name {name!r} (names become "
                "directories under the capture root)"
            )
        budget = self.max_seconds
        if max_seconds is not None:
            try:
                budget = float(max_seconds)
            except (TypeError, ValueError):
                raise ProfilerError(
                    f"maxSeconds must be a number, got {max_seconds!r}"
                ) from None
            if budget <= 0:
                raise ProfilerError("maxSeconds must be > 0")
            budget = min(budget, self.max_seconds)
        logdir = os.path.join(self.root, name)
        # Claim + start_trace are atomic under the lock: jax's
        # profiler is process-global, so two racing starts, or a
        # start racing a stale deadline timer, must serialize here
        # (start_trace only opens the session — milliseconds; the
        # expensive flush happens at stop, which runs its jax call
        # outside the lock behind the _stopping sentinel).  Prune
        # VICTIMS are only chosen after this start is admitted —
        # a refused start must have zero side effects — and the
        # rmtree work runs after the lock releases.
        with self._lock:
            if self._active is not None or self._stopping:
                raise ProfilerConflict(
                    "a profiler capture is already active or "
                    "stopping"
                    + (f" ({self._active['name']!r})"
                       if self._active else "")
                    + "; stop it / retry shortly"
                )
            if os.path.isdir(logdir):
                raise ProfilerConflict(
                    f"capture {name!r} already exists; pick another "
                    "name"
                )
            victims = self._prune_victims(keep=name)
            os.makedirs(logdir, exist_ok=True)
            try:
                import jax

                jax.profiler.start_trace(logdir)
            except BaseException as exc:
                # Another trace (a monitored train job's) may already
                # hold the process-global profiler.  A failed start
                # must never wedge the surface.
                shutil.rmtree(logdir, ignore_errors=True)
                raise ProfilerConflict(
                    f"jax profiler could not start ({exc!r}); "
                    "another trace may be active in this process"
                ) from None
            self._active = active = {
                "name": name, "logdir": logdir,
                "startedAt": time.time(), "deadlineS": budget,
            }
            timer = threading.Timer(
                budget, self._auto_stop, args=(name,)
            )
            timer.daemon = True
            self._deadline_timer = timer
            self.captures_total += 1
            active = dict(active)
        timer.start()
        for victim in victims:
            shutil.rmtree(victim, ignore_errors=True)
        return active

    def stop(self) -> dict:
        """End the active capture; returns its manifest (name, files,
        total bytes).  No active capture → 409."""
        return self._stop_expected(None)

    def _stop_expected(self, expected: str | None) -> dict:
        """Stop the active capture — only if it is still ``expected``
        (None = whatever is active).  The check and the state clear
        are atomic, so a stale deadline timer can never stop the
        FRESH capture an operator started after its own ended; the
        (potentially multi-second) ``stop_trace`` flush itself runs
        OUTSIDE the lock behind the ``_stopping`` sentinel, so status
        and listing requests never stack behind it."""
        with self._lock:
            active = self._active
            if active is None or (
                expected is not None and active["name"] != expected
            ):
                raise ProfilerConflict("no profiler capture is active")
            self._active = None
            self._stopping = True
            timer, self._deadline_timer = self._deadline_timer, None
        try:
            import jax

            jax.profiler.stop_trace()
        except BaseException:  # noqa: BLE001 — files flushed
            pass  # before the failure are still the evidence
        finally:
            with self._lock:
                self._stopping = False
        if timer is not None:
            timer.cancel()
        manifest = {
            "name": active["name"],
            "startedAt": active["startedAt"],
            "stoppedAt": time.time(),
            "durationS": round(time.time() - active["startedAt"], 3),
            "files": _file_manifest(active["logdir"]),
        }
        manifest["totalBytes"] = sum(
            f["bytes"] for f in manifest["files"]
        )
        try:
            with open(
                os.path.join(active["logdir"], _META_FILE), "w"
            ) as fh:
                json.dump(manifest, fh)
        except OSError:
            pass  # listing degrades to the bare directory walk
        return manifest

    def _auto_stop(self, name: str) -> None:
        """Deadline expiry: stop the capture IFF it is still the one
        this timer was armed for (atomic inside _stop_expected)."""
        try:
            self._stop_expected(name)
        except ProfilerConflict:
            return  # lost the race to an operator stop — fine
        with self._lock:
            self.auto_stops += 1

    # -- listing + retrieval -------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            active = dict(self._active) if self._active else None
            stopping = self._stopping
        return {
            "active": active,
            "stopping": stopping,
            "capturesTotal": self.captures_total,
            "autoStops": self.auto_stops,
            "root": self.root,
            "maxSeconds": self.max_seconds,
            "maxCaptures": self.max_captures,
        }

    def list_captures(self) -> list[dict]:
        """Every retained capture, oldest first, with file manifests."""
        if not os.path.isdir(self.root):
            return []
        with self._lock:
            active_name = (
                self._active["name"] if self._active else None
            )
        out = []
        for entry in sorted(os.listdir(self.root)):
            logdir = os.path.join(self.root, entry)
            if not os.path.isdir(logdir):
                continue
            doc = None
            meta = os.path.join(logdir, _META_FILE)
            if os.path.isfile(meta):
                try:
                    with open(meta) as fh:
                        doc = json.load(fh)
                except (OSError, ValueError):
                    doc = None
            if doc is None:
                doc = {"name": entry,
                       "files": _file_manifest(logdir)}
                doc["totalBytes"] = sum(
                    f["bytes"] for f in doc["files"]
                )
            doc["active"] = entry == active_name
            out.append(doc)
        return out

    def capture(self, name: str) -> dict | None:
        for doc in self.list_captures():
            if doc["name"] == name:
                return doc
        return None

    def read_file(self, name: str, rel_path: str) -> bytes:
        """One capture artifact's bytes (the retrieval half of the
        REST surface).  The resolved path must stay inside the
        capture's directory — ``rel_path`` comes off the wire."""
        if not _NAME_RE.fullmatch(name):
            raise ProfilerError(f"invalid capture name {name!r}")
        logdir = os.path.realpath(os.path.join(self.root, name))
        target = os.path.realpath(os.path.join(logdir, rel_path))
        if not target.startswith(logdir + os.sep):
            raise ProfilerError(
                f"file path {rel_path!r} escapes the capture"
            )
        try:
            with open(target, "rb") as fh:
                return fh.read()
        except OSError:
            # Plain not-found (→ 404), distinct from the traversal
            # rejection above (→ 406): clients retrying after a stop
            # must be able to tell the two apart.
            raise ProfilerNotFound(
                f"no file {rel_path!r} in capture {name!r}"
            ) from None

    def delete(self, name: str) -> bool:
        """Drop a retained capture (idempotent).  The active capture
        refuses — stop it first."""
        if not _NAME_RE.fullmatch(name):
            raise ProfilerError(f"invalid capture name {name!r}")
        with self._lock:
            if self._active is not None and \
                    self._active["name"] == name:
                raise ProfilerConflict(
                    f"capture {name!r} is active; stop it before "
                    "deleting"
                )
            if self._stopping:
                # A stop's trace flush is in flight (the active slot
                # is already cleared): deleting now would race the
                # flush re-creating the dir with partial files.
                raise ProfilerConflict(
                    "a capture is stopping; retry shortly"
                )
        logdir = os.path.join(self.root, name)
        if not os.path.isdir(logdir):
            return False
        shutil.rmtree(logdir, ignore_errors=True)
        return True

    def _prune_victims(self, keep: str) -> list[str]:
        """Bounded capture dir: beyond ``max_captures`` (counting the
        ADMITTED capture about to start), the OLDEST capture dirs are
        the victims — newest evidence wins.  Selection only (the
        caller deletes outside the lock); the new capture — and, for
        safety, any active one — is never a victim."""
        if not os.path.isdir(self.root):
            return []
        active_name = (
            self._active["name"] if self._active else None
        )
        entries = []
        for entry in os.listdir(self.root):
            logdir = os.path.join(self.root, entry)
            if entry in (keep, active_name) or not os.path.isdir(
                logdir
            ):
                continue
            try:
                entries.append((os.path.getmtime(logdir), logdir))
            except OSError:
                continue
        entries.sort()
        excess = len(entries) - (self.max_captures - 1)
        return [logdir for _mtime, logdir in entries[:max(0, excess)]]

    def close(self) -> None:
        """Server shutdown: end any active capture so the profiler
        does not outlive the process's surface."""
        with self._lock:
            active = self._active is not None
        if active:
            try:
                self.stop()
            except ProfilerConflict:
                pass


def _file_manifest(logdir: str) -> list[dict]:
    files = []
    for dirpath, _dirs, names in os.walk(logdir):
        for fname in names:
            if fname == _META_FILE:
                continue
            path = os.path.join(dirpath, fname)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            files.append({
                "path": os.path.relpath(path, logdir),
                "bytes": size,
            })
    files.sort(key=lambda f: f["path"])
    return files
