"""Unified observability layer: metrics registry, Prometheus
exposition (obs/metrics.py), end-to-end job tracing (obs/tracing.py),
cost accounting / device-time attribution (obs/costs.py), on-demand
profiler capture (obs/profiling.py), windowed time-series rollups
(obs/rollup.py) and SLO burn-rate alerting (obs/slo.py).

One coherent surface over what previously lived on four disjoint JSON
endpoints: ``GET /metrics.prom`` exposes every subsystem's counters
and histograms in Prometheus text format, and
``GET /observability/jobs/<name>/trace`` serves the span tree of a
job's life (queue wait → lease → compile → per-epoch steps), keyed by
the ``X-Request-Id`` the API mints or echoes.

Knobs: ``LO_TPU_OBS_*`` (config.py ObsConfig).
"""

from learningorchestra_tpu.obs.metrics import (  # noqa: F401
    Family,
    MetricsRegistry,
    get_registry,
    reset_registry,
)
from learningorchestra_tpu.obs.tracing import (  # noqa: F401
    JobTrace,
    current_trace,
    get_request_id,
    new_request_id,
    new_trace,
    record_span,
    span,
    span_tree,
)

__all__ = [
    "Family",
    "JobTrace",
    "MetricsRegistry",
    "current_trace",
    "get_registry",
    "get_request_id",
    "new_request_id",
    "new_trace",
    "record_span",
    "reset_registry",
    "span",
    "span_tree",
]
