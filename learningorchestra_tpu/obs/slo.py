"""Declarative SLO objectives + multi-window burn-rate alerting.

The rollup engine (obs/rollup.py) gives the process a time dimension;
this module puts the production-serving contract on top of it, the
layer the Gemma TPU serving and pjit/TPUv4 scaling papers' fleets
operate on: **objectives** with error budgets, **burn rates** over two
windows, and an **alert state machine** with pluggable delivery.

Objectives (built from config, one evaluation per rollup tick):

- ``route-availability`` — non-5xx fraction of all HTTP requests
  (``lo_http_requests_total`` status-class deltas);
- ``predict-latency`` — per served model, the fraction of predicts
  completing under ``LO_TPU_SLO_PREDICT_P99_MS``
  (``lo_serving_predict_duration_seconds`` bucket deltas; one alert
  instance per model label);
- ``job-success`` — finished / (finished + failed + deadline) over
  ``lo_jobs_total`` deltas (preempted-and-retried attempts are not
  failures).

**Burn rate** is bad-fraction divided by the error budget
(``1 - target``): burn 1.0 spends the budget exactly over the window,
burn N spends it N× too fast.  An alert requires the burn above
``LO_TPU_SLO_BURN`` over BOTH the fast and the slow window — the fast
window catches the page-now spike, the slow window keeps a brief blip
from paging (the standard multi-window guard).  States:

    inactive → pending (breach) → firing (held ``for_s``)
            → resolved (breach-free ``resolve_s``) → inactive

Transitions deliver to every registered sink: a structured log line
always; a webhook POST when ``LO_TPU_SLO_WEBHOOK`` is set (off by
default — alert *evaluation* is always on, *delivery* beyond the log
is opt-in).  ``GET /observability/alerts`` serves the live state and
a bounded resolved-alert history; ``lo_alert_active`` /
``lo_slo_burn_rate`` / ``lo_slo_error_budget_remaining`` mirror it on
``/metrics.prom``.

Knobs: ``LO_TPU_SLO_*`` (config.py SLOConfig).
"""

from __future__ import annotations

import collections
import json
import threading
import time

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.log import get_logger, kv

logger = get_logger("slo")

__all__ = [
    "SLOService",
    "burn_rate",
    "ensure_service",
    "get_service",
    "on_tick",
    "reset_service",
]


def burn_rate(bad: float, total: float, target: float) -> float | None:
    """Bad-fraction over the window divided by the error budget
    (``1 - target``).  ``None`` with no traffic — no data is not the
    same as a healthy 0 (an idle service must neither page nor mark
    its budget spent)."""
    if total <= 0:
        return None
    budget = 1.0 - target
    if budget <= 0:
        return None
    return (bad / total) / budget


class _Objective:
    """One declarative objective: knows how to read its good/bad
    counts for a window from the rollup engine."""

    def __init__(self, name: str, kind: str, target: float, **spec):
        self.name = name
        self.kind = kind
        self.target = target
        self.source = spec.pop("source", "config")
        self.spec = spec

    def instances(self, engine) -> list[str]:
        if self.kind == "latency":
            return engine.label_values(
                self.spec.get(
                    "metric", "lo_serving_predict_duration_seconds"
                ),
                "model",
            )
        return ["all"]

    def counts(self, engine, instance: str, window_s: float,
               now: float):
        """``(bad, total)`` over the window, or ``None`` (no data)."""
        if self.kind == "availability":
            # Optional per-route filter (ad-hoc runtime objectives):
            # a drill can hold ONE route to its own availability
            # target instead of the fleet-wide aggregate.
            route = self.spec.get("route")
            total = engine.counter_delta(
                "lo_http_requests_total",
                {"route": route} if route else None,
                window_s, now=now,
            )
            if total is None or total <= 0:
                return None
            bad_labels = {"status": "5xx"}
            if route:
                bad_labels["route"] = route
            bad = engine.counter_delta(
                "lo_http_requests_total", bad_labels,
                window_s, now=now,
            ) or 0.0
            return bad, total
        if self.kind == "latency":
            frac = engine.fraction_below(
                self.spec.get(
                    "metric", "lo_serving_predict_duration_seconds"
                ),
                {"model": instance},
                self.spec["threshold_s"], window_s, now=now,
            )
            if frac is None:
                return None
            good, total = frac
            return max(0.0, total - good), total
        # job_success
        good = engine.counter_delta(
            "lo_jobs_total", {"state": "finished"}, window_s, now=now
        )
        bad = 0.0
        for state in ("failed", "deadline"):
            bad += engine.counter_delta(
                "lo_jobs_total", {"state": state}, window_s, now=now
            ) or 0.0
        if good is None and bad <= 0:
            return None
        total = (good or 0.0) + bad
        return (bad, total) if total > 0 else None

    def to_doc(self) -> dict:
        doc = {"name": self.name, "kind": self.kind,
               "target": self.target,
               "errorBudget": round(1.0 - self.target, 6),
               "source": self.source}
        if "threshold_s" in self.spec:
            doc["thresholdMs"] = self.spec["threshold_s"] * 1e3
        if "metric" in self.spec:
            doc["metric"] = self.spec["metric"]
        if "route" in self.spec:
            doc["route"] = self.spec["route"]
        return doc


class SLOService:
    """Objective evaluation + alert state machine + delivery."""

    #: Resolved/fired transitions retained for the REST history view.
    HISTORY = 64

    def __init__(self, cfg):
        self.cfg = cfg
        self._lock = make_lock("SLOService._lock")
        self.objectives: list[_Objective] = []
        if cfg.availability_target > 0:
            self.objectives.append(_Objective(
                "route-availability", "availability",
                cfg.availability_target,
            ))
        if cfg.predict_p99_ms > 0:
            self.objectives.append(_Objective(
                "predict-latency", "latency", cfg.predict_target,
                threshold_s=cfg.predict_p99_ms / 1e3,
            ))
        if getattr(cfg, "decode_ttft_ms", 0) > 0:
            # Streaming decode: time-to-first-token per model — the
            # latency SLO for the SSE surface, over the decode
            # engine's own TTFT histogram instead of predict's.
            self.objectives.append(_Objective(
                "decode-ttft", "latency", cfg.decode_ttft_target,
                threshold_s=cfg.decode_ttft_ms / 1e3,
                metric="lo_serving_decode_ttft_seconds",
            ))
        if cfg.job_success_target > 0:
            self.objectives.append(_Objective(
                "job-success", "job_success", cfg.job_success_target,
            ))
        # (objective, instance) -> alert state dict.
        self._alerts: dict[tuple, dict] = {}
        self.history: collections.deque = collections.deque(
            maxlen=self.HISTORY
        )
        self.evaluations = 0
        self._sinks = [self._log_sink]
        if cfg.webhook:
            self._sinks.append(self._webhook_sink)

    # -- runtime objectives --------------------------------------------------

    #: Valid kinds for ad-hoc objectives (POST /observability/slo).
    KINDS = ("availability", "latency", "job_success")
    #: Runtime-registered objectives are bounded: every objective
    #: costs two window reads per instance per tick.
    MAX_OBJECTIVES = 32

    def add_objective(self, name: str, kind: str, target: float,
                      **spec) -> dict:
        """Register an ad-hoc objective at runtime (the drill
        surface): ``availability`` takes an optional ``route`` filter,
        ``latency`` takes ``threshold_s`` and an optional histogram
        ``metric``.  Raises ValueError on a bad spec, an existing
        name, or the objective cap."""
        name = str(name or "").strip()
        if not name:
            raise ValueError("objective needs a non-empty 'name'")
        if kind not in self.KINDS:
            raise ValueError(
                f"unknown objective kind {kind!r} "
                f"(one of {list(self.KINDS)})"
            )
        target = float(target)
        if not 0.0 < target < 1.0:
            # Same zero-budget convention the boot knobs enforce: a
            # target of 1.0 cannot burn.
            raise ValueError(
                f"target {target!r} must be a fraction in (0, 1)"
            )
        if kind == "latency":
            if float(spec.get("threshold_s") or 0) <= 0:
                raise ValueError(
                    "latency objectives need a positive thresholdMs"
                )
            spec["threshold_s"] = float(spec["threshold_s"])
        spec = {k: v for k, v in spec.items() if v is not None}
        obj = _Objective(name, kind, target, source="runtime", **spec)
        with self._lock:
            if any(o.name == name for o in self.objectives):
                raise ValueError(
                    f"objective {name!r} already exists"
                )
            if len(self.objectives) >= self.MAX_OBJECTIVES:
                raise ValueError(
                    f"objective cap reached ({self.MAX_OBJECTIVES})"
                )
            self.objectives.append(obj)
        return obj.to_doc()

    def remove_objective(self, name: str) -> bool:
        """Drop a runtime objective and its live alert rows (the
        transition history keeps the record).  Config-built
        objectives are deliberately not removable — they are the
        deployment's contract, not a drill's."""
        with self._lock:
            for obj in self.objectives:
                if obj.name == name and obj.source == "runtime":
                    self.objectives.remove(obj)
                    for key in list(self._alerts):
                        if key[0] == name:
                            del self._alerts[key]
                    return True
        return False

    def _objectives_snapshot(self) -> list:
        with self._lock:
            return list(self.objectives)

    # -- sinks ---------------------------------------------------------------

    def add_sink(self, fn) -> None:
        """Register an alert-transition consumer: ``fn(event_dict)``,
        called for firing and resolved transitions.  Exceptions are
        swallowed per sink — a broken pager must not break the rest."""
        with self._lock:
            self._sinks.append(fn)

    @staticmethod
    def _log_sink(event: dict) -> None:
        logger.warning(kv(
            event=f"slo_alert_{event['state']}", slo=event["slo"],
            instance=event["instance"],
            burnFast=event.get("burnFast"),
            burnSlow=event.get("burnSlow"),
        ))

    def _webhook_sink(self, event: dict) -> None:
        """Fire-and-forget POST so a slow receiver never stalls the
        rollup tick the evaluation rides."""
        url = self.cfg.webhook

        def _post():
            import urllib.request

            req = urllib.request.Request(
                url, data=json.dumps(event).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                urllib.request.urlopen(req, timeout=5).close()
            except Exception as exc:  # noqa: BLE001 — best-effort
                logger.warning(kv(
                    event="slo_webhook_failed", url=url,
                    error=repr(exc),
                ))

        threading.Thread(
            target=_post, name="slo-webhook", daemon=True
        ).start()

    def _deliver(self, event: dict) -> None:
        with self._lock:
            sinks = list(self._sinks)
            self.history.append(event)
        for sink in sinks:
            try:
                sink(event)
            except Exception:  # noqa: BLE001
                logger.exception("alert sink failed")

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, engine, now: float | None = None) -> list[dict]:
        """One pass over every (objective, instance) against the
        rollup windows; returns the delivered transition events.
        Called from the rollup tick; public for tests and the bench
        probe."""
        if not self.cfg.enabled:
            return []
        now = time.monotonic() if now is None else float(now)
        events: list[dict] = []
        evaluated: set[tuple] = set()
        with self._lock:
            self.evaluations += 1
        for obj in self._objectives_snapshot():
            for instance in obj.instances(engine):
                evaluated.add((obj.name, instance))
                fast = obj.counts(
                    engine, instance, self.cfg.fast_window_s, now
                )
                slow = obj.counts(
                    engine, instance, self.cfg.slow_window_s, now
                )
                burn_fast = burn_rate(*fast, obj.target) \
                    if fast else None
                burn_slow = burn_rate(*slow, obj.target) \
                    if slow else None
                breach = (
                    burn_fast is not None and burn_slow is not None
                    and burn_fast >= self.cfg.burn_threshold
                    and burn_slow >= self.cfg.burn_threshold
                )
                event = self._transition(
                    obj, instance, breach, burn_fast, burn_slow, now
                )
                if event is not None:
                    events.append(event)
        # Garbage collection, so the live view and the Prometheus
        # mirror cannot grow stale rows forever: a ``resolved`` alert
        # decays to ``inactive`` after one more resolve window (the
        # transition history keeps the record), and an inactive entry
        # whose instance no longer exists (a per-model objective's
        # model dropped off the rollup series) is removed entirely.
        with self._lock:
            for key in list(self._alerts):
                st = self._alerts[key]
                if (
                    st["state"] == "resolved"
                    and now - st.get("resolvedAt", now)
                    >= self.cfg.resolve_s
                ):
                    st["state"] = "inactive"
                if st["state"] == "inactive" and key not in evaluated:
                    del self._alerts[key]
        for event in events:
            self._deliver(event)
        return events

    def _transition(self, obj, instance, breach, burn_fast,
                    burn_slow, now) -> dict | None:
        """Advance one alert's state machine; returns the event to
        deliver (firing/resolved) or None."""
        key = (obj.name, instance)
        with self._lock:
            st = self._alerts.get(key)
            if st is None:
                st = self._alerts[key] = {
                    "slo": obj.name, "instance": instance,
                    "state": "inactive",
                    "pendingSince": None, "firingSince": None,
                    "okSince": None,
                }
            st["burnFast"] = burn_fast
            st["burnSlow"] = burn_slow
            st["target"] = obj.target
            st["evaluatedAt"] = time.time()
            state = st["state"]
            if breach:
                st["okSince"] = None
                if state in ("inactive", "resolved"):
                    st["state"] = "pending"
                    st["pendingSince"] = now
                    st["pendingSinceWall"] = time.time()
                    state = "pending"
                if (
                    state == "pending"
                    and now - st["pendingSince"] >= self.cfg.for_s
                ):
                    st["state"] = "firing"
                    st["firingSince"] = now
                    st["firingSinceWall"] = time.time()
                    return self._event(st, "firing")
                return None
            # No breach: pending collapses immediately (it never
            # paged); firing needs resolve_s of clean air first.
            if state == "pending":
                st["state"] = "inactive"
                st["pendingSince"] = None
            elif state == "firing":
                if st["okSince"] is None:
                    st["okSince"] = now
                if now - st["okSince"] >= self.cfg.resolve_s:
                    st["state"] = "resolved"
                    st["resolvedAt"] = now
                    st["resolvedAtWall"] = time.time()
                    event = self._event(st, "resolved")
                    event["firedForS"] = round(
                        now - st["firingSince"], 3
                    )
                    st["firingSince"] = None
                    st["pendingSince"] = None
                    st["okSince"] = None
                    return event
            return None

    @staticmethod
    def _event(st: dict, state: str) -> dict:
        return {
            "state": state,
            "slo": st["slo"],
            "instance": st["instance"],
            "burnFast": st["burnFast"],
            "burnSlow": st["burnSlow"],
            "target": st["target"],
            "t": time.time(),
        }

    # -- views ---------------------------------------------------------------

    def alerts(self) -> dict:
        """The ``GET /observability/alerts`` body: live alert states
        (pending/firing first), the bounded transition history, and
        the evaluation config that produced them."""
        with self._lock:
            live = [dict(st) for st in self._alerts.values()]
            # Copied under the SAME lock _deliver appends under — an
            # alert transitioning while the drill polls must not
            # mutate the deque mid-iteration.
            history = list(self.history)
        order = {"firing": 0, "pending": 1, "resolved": 2,
                 "inactive": 3}
        live.sort(key=lambda st: (order.get(st["state"], 3),
                                  st["slo"], st["instance"]))
        return {
            "alerts": live,
            "firing": [
                st for st in live if st["state"] == "firing"
            ],
            "history": history,
            "config": {
                "enabled": self.cfg.enabled,
                "fastWindowS": self.cfg.fast_window_s,
                "slowWindowS": self.cfg.slow_window_s,
                "burnThreshold": self.cfg.burn_threshold,
                "forS": self.cfg.for_s,
                "resolveS": self.cfg.resolve_s,
                "webhook": bool(self.cfg.webhook),
            },
        }

    def status(self) -> dict:
        """The ``GET /observability/slo`` body: every objective with
        its target, budget, live burn rates and budget remaining
        (slow window = the budget period)."""
        docs = []
        with self._lock:
            states = {
                k: dict(v) for k, v in self._alerts.items()
            }
        for obj in self._objectives_snapshot():
            doc = obj.to_doc()
            doc["instances"] = []
            for (slo_name, instance), st in sorted(states.items()):
                if slo_name != obj.name:
                    continue
                burn_slow = st.get("burnSlow")
                doc["instances"].append({
                    "instance": instance,
                    "state": st["state"],
                    "burnFast": st.get("burnFast"),
                    "burnSlow": burn_slow,
                    "budgetRemaining": (
                        round(1.0 - burn_slow, 6)
                        if burn_slow is not None else None
                    ),
                })
            docs.append(doc)
        return {
            "enabled": self.cfg.enabled,
            "objectives": docs,
            "evaluations": self.evaluations,
        }

    def prom_families(self) -> list:
        """The Prometheus mirror: lo_slo_burn_rate (both windows),
        lo_alert_active (1 = firing), lo_slo_error_budget_remaining
        (slow window as the budget period; negative = overdrawn)."""
        from learningorchestra_tpu.obs.metrics import Family

        burn = Family(
            "gauge", "lo_slo_burn_rate",
            "Error-budget burn rate per SLO instance and window "
            "(1.0 spends the budget exactly over the window).",
        )
        active = Family(
            "gauge", "lo_alert_active",
            "1 while the SLO alert is firing, else 0.",
        )
        budget = Family(
            "gauge", "lo_slo_error_budget_remaining",
            "Error budget left over the slow window (1 = untouched, "
            "negative = overdrawn).",
        )
        with self._lock:
            states = [dict(st) for st in self._alerts.values()]
        for st in states:
            labels = {"slo": st["slo"], "instance": st["instance"]}
            if st.get("burnFast") is not None:
                burn.sample(st["burnFast"], window="fast", **labels)
            if st.get("burnSlow") is not None:
                burn.sample(st["burnSlow"], window="slow", **labels)
                budget.sample(1.0 - st["burnSlow"], **labels)
            active.sample(
                1 if st["state"] == "firing" else 0, **labels
            )
        return [burn, active, budget]


# -- process-wide singleton ---------------------------------------------------

_service: SLOService | None = None
_service_lock = make_lock("slo._service_lock")


def get_service() -> SLOService:
    """The process-wide service, built from config on first use."""
    global _service
    with _service_lock:
        if _service is None:
            from learningorchestra_tpu.config import get_config

            _service = SLOService(get_config().slo)
        return _service


def ensure_service(cfg) -> SLOService:
    """Build the singleton from ``cfg`` if none exists yet (API-server
    construction), then return it."""
    global _service
    with _service_lock:
        if _service is None:
            _service = SLOService(cfg)
        return _service


def reset_service(cfg=None) -> SLOService:
    """Replace the singleton (tests, the bench probe)."""
    global _service
    with _service_lock:
        _service = None if cfg is None else SLOService(cfg)
    return get_service() if cfg is None else _service


def on_tick(engine, now: float | None = None) -> None:
    """Rollup-tick hook: evaluate the singleton IF one has been
    configured (API server boot, a test, the bench).  A bare rollup
    engine with no SLO service evaluates nothing — objective state
    must not mint itself as a side effect of unrelated ticks."""
    with _service_lock:
        service = _service
    if service is not None:
        service.evaluate(engine, now=now)
