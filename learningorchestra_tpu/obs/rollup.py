"""Windowed time-series rollups over the metrics registry.

The obs plane before this module answered "what is happening right
now" — every family on ``/metrics.prom`` is an instantaneous counter,
gauge or cumulative histogram — but nothing in-process could answer
"what was p99 over the last 5 minutes" or "how fast is the queue
growing".  External Prometheus gets that for free from its TSDB; the
system itself (SLO evaluation, the fleet autoscaler's slope trigger,
an operator curl) had no time dimension at all.

The :class:`RollupEngine` is that dimension, kept deliberately small:

- every ``tick_s`` seconds it snapshots a SELECTED set of registry
  families (``MetricsRegistry.collect_all`` — push metrics and pull
  collectors through one surface) into per-series **bounded ring
  buffers** (``points`` entries each, ``max_series`` series total —
  a label explosion drops new series, counted, instead of growing
  memory);
- windowed views derive on demand from the rings: counter **rates**
  (delta/dt with reset detection), gauge **min/avg/max/last**,
  histogram **quantiles from cumulative-bucket deltas** (the
  Prometheus ``histogram_quantile`` interpolation, applied to the
  window's bucket increments), and least-squares **slope** (the
  autoscaler's queue-growth signal);
- ``GET /observability/timeseries`` serves the raw points and the
  derived views; ``obs/slo.py`` evaluates its objectives against the
  same windows on every tick.

One engine per process (module singleton, like the metrics registry
and the cost ledgers); the engine reads whatever registry is CURRENT
at each tick, so a test's ``reset_registry()`` needs no rebind dance.
``tick()`` is public and takes an explicit ``now`` so tests drive
synthetic schedules deterministically without the thread.

Knobs: ``LO_TPU_ROLLUP_*`` (config.py RollupConfig).
"""

from __future__ import annotations

import collections
import threading
import time

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.log import get_logger

logger = get_logger("rollup")

__all__ = [
    "CORE_FAMILIES",
    "RollupEngine",
    "ensure_engine",
    "get_engine",
    "quantile_from_deltas",
    "reset_engine",
]

#: Families every deployment tracks (LO_TPU_ROLLUP_FAMILIES adds more).
#: Each is bounded-cardinality by construction: routes come from the
#: fixed route table, job classes from the service types, models from
#: the serving registry's max_models cap.
CORE_FAMILIES = (
    "lo_http_requests_total",
    "lo_http_request_duration_seconds",
    "lo_jobs_total",
    "lo_jobs_queue_depth",
    "lo_lease_devices",
    "lo_serving_events_total",
    "lo_serving_queue_depth",
    "lo_serving_model_queue_depth",
    "lo_serving_predict_duration_seconds",
    "lo_serving_replicas",
    "lo_serving_decode_ttft_seconds",
    "lo_serving_decode_itl_seconds",
    "lo_serving_decode_tokens_total",
    "lo_serving_decode_active_streams",
    "lo_serving_decode_free_slots",
    "lo_cluster_claims_total",
    "lo_cluster_engines",
    "lo_admission_rejections_total",
)


def quantile_from_deltas(edges, deltas, q: float):
    """Prometheus-style ``histogram_quantile`` over one window's
    per-bucket count increments.

    ``edges`` are the finite bucket upper bounds (ascending);
    ``deltas`` has ``len(edges) + 1`` entries — the last is the +Inf
    bucket.  Linear interpolation inside the bucket the rank lands in
    (lower bound 0 for the first); a rank in the +Inf bucket returns
    the highest finite edge, never an invented value.  ``None`` when
    the window saw no observations."""
    total = sum(deltas)
    if total <= 0:
        return None
    rank = min(max(q, 0.0), 1.0) * total
    cum = 0.0
    lo = 0.0
    for edge, d in zip(edges, deltas):
        if d > 0 and cum + d >= rank:
            return lo + (edge - lo) * ((rank - cum) / d)
        cum += d
        lo = edge
    return float(edges[-1])


def _hist_deltas(pts) -> tuple:
    """``(per_bucket_deltas, count, sum)`` between a histogram
    window's first and last points, with counter-reset detection —
    the ONE delta body hist_window / fraction_below / the REST view
    all share."""
    first, last = pts[0], pts[-1]
    if last[4] < first[4]:  # counter reset: window = newest alone
        cum_d, n, s = list(last[2]), last[4], last[3]
    else:
        cum_d = [b - a for a, b in zip(first[2], last[2])]
        n, s = last[4] - first[4], last[3] - first[3]
    per_bucket = [cum_d[0]] + [
        max(0.0, b - a) for a, b in zip(cum_d, cum_d[1:])
    ]
    return per_bucket, n, s


def _pts_slope(pts) -> float | None:
    """Least-squares value-per-second slope over one series' points."""
    if len(pts) < 2:
        return None
    t0 = pts[0][0]
    xs = [pt[0] - t0 for pt in pts]
    ys = [pt[2] for pt in pts]
    n = len(xs)
    mx, my = sum(xs) / n, sum(ys) / n
    var = sum((x - mx) ** 2 for x in xs)
    if var <= 0:
        return None
    cov = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    return cov / var


class _Series:
    """One tracked (family, label-set): a bounded ring of snapshots.

    Scalar points: ``(mono, wall, value)``.  Histogram points:
    ``(mono, wall, cum, sum, count)`` with ``cum`` the cumulative
    bucket counts INCLUDING the +Inf bucket."""

    __slots__ = ("name", "kind", "labels", "edges", "ring")

    def __init__(self, name, kind, labels, edges, maxlen):
        self.name = name
        self.kind = kind
        self.labels = labels
        self.edges = edges
        self.ring = collections.deque(maxlen=maxlen)

    def window_points(self, now: float, window_s: float) -> list:
        """Points inside the window PLUS the baseline point just
        before it (deltas need the value at the window's left edge;
        without it a window shorter than one tick would always read
        empty)."""
        cut = now - window_s
        pts = list(self.ring)
        start = 0
        for i, pt in enumerate(pts):
            if pt[0] <= cut:
                start = i
            else:
                break
        return pts[start:]


class RollupEngine:
    """Tick-driven snapshots + windowed derivation (module docstring)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.families = tuple(dict.fromkeys(
            CORE_FAMILIES + tuple(cfg.families)
        ))
        self.points = max(2, int(cfg.points))
        self.max_series = max(1, int(cfg.max_series))
        self._lock = make_lock("RollupEngine._lock")
        self._series: dict[tuple, _Series] = {}
        self.ticks = 0
        #: Snapshots dropped because the engine was at max_series —
        #: one per observation, mirroring the registry's overflow
        #: counter semantics.
        self.dropped_series = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Start the daemon (idempotent; no-op when disabled or
        tick_s <= 0 — tests drive tick() directly).  Re-armable after
        :meth:`stop`: the singleton outlives any one API server, so a
        new server's construction revives the clock a previous
        server's shutdown stopped."""
        with self._lock:
            if (
                (self._thread is not None and self._thread.is_alive())
                or not self.cfg.enabled
                or self.cfg.tick_s <= 0
            ):
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="obs-rollup", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        """Stop the daemon (API-server shutdown: a demoted/stopped
        node must not keep evaluating SLOs over frozen windows or
        paging a webhook).  tick() stays callable; start() re-arms."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.tick_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a dead rollup loop is
                # every SLO silently frozen; survive any one tick.
                logger.exception("rollup tick failed")

    # -- ingest --------------------------------------------------------------

    def tick(self, now: float | None = None) -> int:
        """One snapshot pass; returns the number of samples ingested.
        ``now`` is a monotonic timestamp — tests pass synthetic values
        to replay schedules deterministically.

        Cost note: ``collect_all`` runs every registered pull
        collector (they emit whole family groups; per-family skipping
        is not knowable up front), so one tick costs about one
        ``/metrics.prom`` exposition pass — the same class of work a
        Prometheus scrape at the same cadence would do.  Deployments
        sensitive to that trade raise ``LO_TPU_ROLLUP_TICK_S``."""
        if not self.cfg.enabled:
            return 0
        from learningorchestra_tpu.obs.metrics import get_registry

        mono = time.monotonic() if now is None else float(now)
        wall = time.time()
        samples = get_registry().collect_all(names=self.families)
        ingested = 0
        with self._lock:
            self.ticks += 1
            for s in samples:
                key = (
                    s["name"],
                    tuple(sorted(s["labels"].items())),
                )
                series = self._series.get(key)
                if series is None:
                    if len(self._series) >= self.max_series:
                        self.dropped_series += 1
                        continue
                    series = self._series[key] = _Series(
                        s["name"], s["kind"], dict(s["labels"]),
                        tuple(s.get("edges") or ()), self.points,
                    )
                    # Synthetic zero birth point: registry counters
                    # and histograms are created at 0 in-process, so
                    # a series first sighted mid-stream (the first
                    # 5xx, a new model's first predict) gets its full
                    # increment into the window instead of a flat
                    # line at its birth value — without it the
                    # availability drill's error burst would be
                    # invisible to every delta.  Gauges get none: a
                    # fabricated 0 would distort min/avg.
                    if s["kind"] == "histogram":
                        series.ring.append((
                            mono - 1e-6, wall,
                            (0,) * len(s["cum"]), 0.0, 0,
                        ))
                    elif s["kind"] == "counter":
                        series.ring.append((mono - 1e-6, wall, 0.0))
                if s["kind"] == "histogram":
                    series.ring.append(
                        (mono, wall, s["cum"], s["sum"], s["count"])
                    )
                else:
                    series.ring.append((mono, wall, s["value"]))
                ingested += 1
        # SLO evaluation rides the same clock: one tick = one snapshot
        # + one objective pass, so alert timing is a function of
        # tick_s alone (the drill's determinism).
        try:
            from learningorchestra_tpu.obs import slo as obs_slo

            obs_slo.on_tick(self, now=mono)
        except Exception:  # noqa: BLE001 — a broken objective must
            logger.exception("slo evaluation failed")  # not stop ingest
        return ingested

    # -- series access -------------------------------------------------------

    def _match(self, name: str, labels: dict | None) -> list:
        with self._lock:
            return [
                s for (n, _k), s in self._series.items()
                if n == name and (
                    not labels
                    or all(
                        s.labels.get(k) == str(v)
                        for k, v in labels.items()
                    )
                )
            ]

    def label_values(self, name: str, label: str) -> list[str]:
        """Distinct values of one label across a family's tracked
        series (SLO instance discovery: one predict-latency objective
        instance per served model)."""
        with self._lock:
            return sorted({
                s.labels[label]
                for (n, _k), s in self._series.items()
                if n == name and label in s.labels
            })

    # -- derived views -------------------------------------------------------

    @staticmethod
    def _delta(first, last) -> float:
        """Counter increment with reset detection: a restart that
        zeroed the counter reports the post-reset value instead of a
        negative delta (the Prometheus ``increase()`` convention)."""
        d = last - first
        return float(last) if d < 0 else float(d)

    def counter_delta(self, name: str, labels: dict | None,
                      window_s: float,
                      now: float | None = None) -> float | None:
        """Summed increment over the window across matching series;
        ``None`` when nothing is tracked yet."""
        now = time.monotonic() if now is None else now
        total, any_pts = 0.0, False
        for series in self._match(name, labels):
            pts = series.window_points(now, window_s)
            if len(pts) >= 2:
                any_pts = True
                total += self._delta(pts[0][2], pts[-1][2])
        return total if any_pts else None

    def rate(self, name: str, labels: dict | None, window_s: float,
             now: float | None = None) -> float | None:
        """Counter increments per second, averaged over the WHOLE
        window (a series younger than the window was semantically at
        0 before its birth, so the short observed span must not
        inflate the rate)."""
        if window_s <= 0:
            return None
        delta = self.counter_delta(name, labels, window_s, now=now)
        return None if delta is None else delta / window_s

    def gauge_window(self, name: str, labels: dict | None,
                     window_s: float,
                     now: float | None = None) -> dict | None:
        """min/avg/max/last over matching gauge points in the window
        (multi-series matches pool their points).  Strictly in-window
        points only: the pre-window baseline window_points keeps for
        counter/histogram DELTAS would report a stale series' ancient
        value as live data here — a dissolved model's frozen queue
        depth must read as no data, not as its hour-old level."""
        now = time.monotonic() if now is None else now
        cut = now - window_s
        values = []
        for series in self._match(name, labels):
            values += [
                pt[2] for pt in series.window_points(now, window_s)
                if pt[0] > cut
            ]
        if not values:
            return None
        return {
            "min": min(values),
            "avg": sum(values) / len(values),
            "max": max(values),
            "last": values[-1],
        }

    def hist_window(self, name: str, labels: dict | None,
                    window_s: float, qs=(0.5, 0.9, 0.95, 0.99),
                    now: float | None = None) -> dict | None:
        """Windowed histogram view from cumulative-bucket deltas:
        per-quantile estimates, observation count and mean over the
        window.  Multi-series matches sum their bucket deltas (the
        aggregate distribution)."""
        now = time.monotonic() if now is None else now
        deltas, edges = None, None
        count, hsum = 0.0, 0.0
        for series in self._match(name, labels):
            pts = series.window_points(now, window_s)
            if len(pts) < 2 or not series.edges:
                continue
            per_bucket, n, s = _hist_deltas(pts)
            count += n
            hsum += s
            if deltas is None:
                deltas, edges = per_bucket, series.edges
            elif series.edges == edges:
                deltas = [a + b for a, b in zip(deltas, per_bucket)]
        if deltas is None or count <= 0:
            return None
        return {
            "count": count,
            "sum": hsum,
            "avg": hsum / count,
            "quantiles": {
                f"p{round(q * 100) if q < 0.995 else '99.9'}":
                    quantile_from_deltas(edges, deltas, q)
                for q in qs
            },
        }

    def fraction_below(self, name: str, labels: dict | None,
                       threshold: float, window_s: float,
                       now: float | None = None):
        """``(good, total)`` observation counts over the window, where
        good = observations <= the smallest bucket edge >= threshold
        (bucket resolution rounds UP — an SLO threshold between edges
        credits the conservative bucket).  The latency-SLO primitive."""
        now = time.monotonic() if now is None else now
        good, total = 0.0, 0.0
        seen = False
        for series in self._match(name, labels):
            pts = series.window_points(now, window_s)
            if len(pts) < 2 or not series.edges:
                continue
            per_bucket, n, _s = _hist_deltas(pts)
            if n <= 0:
                continue
            seen = True
            total += n
            idx = None
            for i, edge in enumerate(series.edges):
                if edge >= threshold:
                    idx = i
                    break
            if idx is None:
                # Threshold above every finite edge: observations in
                # the +Inf bucket are of UNKNOWN magnitude — credit
                # only those under the largest finite edge (counting
                # them good would make the latency SLO unfireable).
                idx = len(series.edges) - 1
            good += sum(per_bucket[:idx + 1])
        return (good, total) if seen else None

    def slope(self, name: str, labels: dict | None, window_s: float,
              now: float | None = None) -> float | None:
        """Least-squares growth rate (value units per second) over the
        window's points, summed across matching series per timestamp —
        the fleet autoscaler's queue-ramp signal.  ``None`` below two
        distinct-time points."""
        now = time.monotonic() if now is None else now
        cut = now - window_s
        by_t: dict[float, float] = {}
        for series in self._match(name, labels):
            for pt in series.window_points(now, window_s):
                if pt[0] > cut:  # gauge semantics: no stale baseline
                    by_t[pt[0]] = by_t.get(pt[0], 0.0) + pt[2]
        # Pool per timestamp, then the ONE least-squares body
        # (_pts_slope) the REST view's per-series slopePerS uses too.
        return _pts_slope([
            (t, None, by_t[t]) for t in sorted(by_t)
        ])

    # -- REST views ----------------------------------------------------------

    def timeseries(self, name: str | None = None,
                   labels: dict | None = None,
                   window_s: float = 300.0,
                   max_points: int = 0) -> dict:
        """The ``GET /observability/timeseries`` body.  Without
        ``name``: the tracked-family directory.  With one: every
        matching series' raw ``[wall_t, ...]`` points plus the derived
        windowed view for its kind."""
        if name is None:
            with self._lock:
                per_family: dict[str, int] = {}
                for (n, _k) in self._series:
                    per_family[n] = per_family.get(n, 0) + 1
            return {
                "families": [
                    {"name": n, "series": per_family.get(n, 0)}
                    for n in self.families
                ],
                **self.status(),
            }
        now = time.monotonic()
        out = []
        # Derived views come from EACH series' already-extracted
        # points — re-running the multi-series window methods per
        # series would rescan the whole table O(series^2).
        for series in self._match(name, labels):
            pts = series.window_points(now, window_s)
            doc: dict = {"labels": series.labels, "kind": series.kind}
            if series.kind == "histogram":
                raw = [
                    [round(pt[1], 3), pt[4]] for pt in pts
                ]  # wall time + cumulative observation count
                if max_points > 0:
                    raw = raw[-max_points:]
                doc["points"] = raw
                doc["window"] = None
                if len(pts) >= 2 and series.edges:
                    deltas, n, s = _hist_deltas(pts)
                    if n > 0:
                        doc["window"] = {
                            "count": n,
                            "sum": s,
                            "avg": s / n,
                            "quantiles": {
                                f"p{round(q * 100)}":
                                    quantile_from_deltas(
                                        series.edges, deltas, q
                                    )
                                for q in (0.5, 0.9, 0.95, 0.99)
                            },
                        }
            else:
                raw = [[round(pt[1], 3), pt[2]] for pt in pts]
                if max_points > 0:
                    raw = raw[-max_points:]
                doc["points"] = raw
                if series.kind == "counter":
                    doc["ratePerS"] = (
                        self._delta(pts[0][2], pts[-1][2]) / window_s
                        if len(pts) >= 2 and window_s > 0 else None
                    )
                else:
                    cut = now - window_s
                    live = [pt for pt in pts if pt[0] > cut]
                    vals = [pt[2] for pt in live]
                    doc["window"] = {
                        "min": min(vals),
                        "avg": sum(vals) / len(vals),
                        "max": max(vals),
                        "last": vals[-1],
                    } if vals else None
                    doc["slopePerS"] = _pts_slope(live)
            out.append(doc)
        return {
            "name": name,
            "windowS": window_s,
            "series": out,
            "ticks": self.ticks,
        }

    def status(self) -> dict:
        with self._lock:
            return {
                "enabled": self.cfg.enabled,
                "tickS": self.cfg.tick_s,
                "points": self.points,
                "maxSeries": self.max_series,
                "series": len(self._series),
                "droppedSeries": self.dropped_series,
                "ticks": self.ticks,
                "running": self._thread is not None
                and self._thread.is_alive(),
            }

    def prom_families(self) -> list:
        """lo_rollup_* families for the server's pull collector — the
        engine's own health on the surface it rolls up."""
        from learningorchestra_tpu.obs.metrics import Family

        st = self.status()
        return [
            Family(
                "gauge", "lo_rollup_series",
                "Time series tracked in rollup ring buffers.",
            ).sample(st["series"]),
            Family(
                "counter", "lo_rollup_ticks_total",
                "Rollup snapshot passes.",
            ).sample(st["ticks"]),
            Family(
                "counter", "lo_rollup_dropped_series_total",
                "Snapshots dropped at the LO_TPU_ROLLUP_MAX_SERIES "
                "cap.",
            ).sample(st["droppedSeries"]),
        ]


# -- process-wide singleton ---------------------------------------------------

_engine: RollupEngine | None = None
_engine_lock = make_lock("rollup._engine_lock")


def get_engine() -> RollupEngine:
    """The process-wide engine, built from config on first use."""
    global _engine
    with _engine_lock:
        if _engine is None:
            from learningorchestra_tpu.config import get_config

            _engine = RollupEngine(get_config().rollup)
        return _engine


def ensure_engine(cfg) -> RollupEngine:
    """Build the singleton from ``cfg`` if none exists yet (API-server
    construction: the FIRST server's config wins, mirroring how the
    registry sizes itself), then return it."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = RollupEngine(cfg)
        return _engine


def reset_engine(cfg=None) -> RollupEngine:
    """Replace the singleton (tests, the bench probe); stops any
    running daemon thread first.  ``cfg=None`` rebuilds lazily from
    the global config on next use."""
    global _engine
    with _engine_lock:
        old, _engine = _engine, None
    if old is not None:
        old.stop()
    if cfg is not None:
        with _engine_lock:
            _engine = RollupEngine(cfg)
            return _engine
    return get_engine()
