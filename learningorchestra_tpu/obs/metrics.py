"""Process-wide metrics registry + Prometheus text exposition.

Before this module the system's operational numbers lived on four
disjoint JSON surfaces — the gateway's per-route averages
(GET /metrics), the compiled-program cache counters
(GET /monitoring/<tool>/compileCache), serving stats
(GET /monitoring/<tool>/serving) and the replication status — with no
histograms anywhere and nothing a standard scraper could ingest.  The
reference system's only exporter was KrakenD's :8090 endpoint
(SURVEY §5.1).

This registry is the ONE sink: labeled Counter/Gauge/Histogram
primitives for push-style instrumentation on hot paths (HTTP dispatch,
job queue waits, chip leases), plus pull-style *collectors* that
snapshot existing stats sources (compile cache, serving batchers,
store WALs, lease pool, job queues) at exposition time — those
subsystems already keep exact counters under their own locks, so
mirroring every increment would double-count lock traffic for nothing.

``GET /metrics.prom`` renders the whole registry as Prometheus text
exposition format 0.0.4.  The legacy JSON endpoints remain as views
over the same instrumentation points.

Knobs (config.py ObsConfig, env ``LO_TPU_OBS_*``):

- ``LO_TPU_OBS_ENABLED=0`` turns the layer off: every primitive
  becomes a no-op and tracing stops minting spans — the bench's
  overhead probe measures exactly this delta.
- ``LO_TPU_OBS_MAX_SERIES`` bounds label cardinality per metric: past
  the cap, new label combinations collapse into one ``_overflow``
  series instead of growing memory without bound (a client fuzzing
  URLs must not DoS the registry).
- ``LO_TPU_OBS_BUCKETS_MS`` sets the latency histogram bucket edges.
"""

from __future__ import annotations

import bisect
import math
from typing import Callable, Iterable, Sequence

from learningorchestra_tpu.concurrency_rt import make_lock

__all__ = [
    "Counter",
    "Family",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "reset_registry",
]

#: Reserved label value new series collapse into past the cardinality cap.
OVERFLOW_LABEL = "_overflow"

#: Default latency bucket edges in SECONDS (Prometheus convention).
DEFAULT_LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _labels_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _Metric:
    """Base: one named metric family with a fixed label-name tuple and
    a bounded number of label-value series."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 help_text: str, labelnames: Sequence[str]):
        self.registry = registry
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._series: dict = {}

    def _key(self, labels: dict):
        """Label dict → series key, collapsing into the overflow
        series past the registry's cardinality cap.  Caller holds the
        registry lock.  Hand-rolled loop, no genexpr, type-checked
        str() skip: this runs on every observation of every hot-path
        metric (HTTP dispatch, predict latency)."""
        vals = []
        for n in self.labelnames:
            v = labels.get(n, "")
            vals.append(v if type(v) is str else str(v))
        key = tuple(vals)
        if key in self._series:
            return key
        if len(self._series) >= self.registry.max_series:
            self.registry.series_overflows += 1
            return (OVERFLOW_LABEL,) * len(self.labelnames)
        return key

    def _labels_of(self, key) -> dict:
        return dict(zip(self.labelnames, key))


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        reg = self.registry
        if not reg.enabled:
            return
        with reg.lock:
            key = self._key(labels)
            self._series[key] = self._series.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        reg = self.registry
        if not reg.enabled:
            return
        with reg.lock:
            self._series[self._key(labels)] = float(value)

    def set_max(self, value: float, **labels) -> None:
        """Retain the maximum observed value (the legacy /metrics
        view's per-route ``max_ms``)."""
        reg = self.registry
        if not reg.enabled:
            return
        with reg.lock:
            key = self._key(labels)
            prev = self._series.get(key)
            if prev is None or value > prev:
                self._series[key] = float(value)


class _BoundHistogram:
    """One pre-resolved histogram series: label → key resolution paid
    ONCE at bind time, so a hot path (one predict = one observe) pays
    lock + dict-get + bisect and nothing else."""

    __slots__ = ("metric", "key")

    def __init__(self, metric: "Histogram", key):
        self.metric = metric
        self.key = key

    def observe(self, value: float) -> None:
        metric = self.metric
        reg = metric.registry
        if not reg.enabled:
            return
        with reg.lock:
            metric._observe_key(self.key, value)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics): per series
    stores per-bucket counts plus sum/count; render emits cumulative
    ``_bucket`` lines, ``_sum`` and ``_count``."""

    kind = "histogram"

    def __init__(self, registry, name, help_text, labelnames,
                 buckets: Sequence[float] | None = None):
        super().__init__(registry, name, help_text, labelnames)
        edges = tuple(sorted(buckets or DEFAULT_LATENCY_BUCKETS_S))
        if not edges:
            edges = DEFAULT_LATENCY_BUCKETS_S
        self.buckets = edges

    def observe(self, value: float, **labels) -> None:
        reg = self.registry
        if not reg.enabled:
            return
        with reg.lock:
            self._observe_key(self._key(labels), value)

    def _observe_key(self, key, value: float) -> None:
        """The ONE series-update body (observe() and every bound
        handle share it).  Caller holds the registry lock."""
        state = self._series.get(key)
        if state is None:
            state = self._series[key] = {
                "counts": [0] * len(self.buckets),
                "sum": 0.0,
                "count": 0,
            }
        # First edge >= value, binary-searched: this sits on the
        # predict hot path (one call per request).
        i = bisect.bisect_left(self.buckets, value)
        if i < len(self.buckets):
            state["counts"][i] += 1
        state["sum"] += value
        state["count"] += 1

    def bind(self, **labels) -> _BoundHistogram:
        """Resolve one series' key now and return a
        :class:`_BoundHistogram` that observes without per-call label
        resolution.  The cardinality cap applies at bind time (a
        bound overflow series stays collapsed)."""
        with self.registry.lock:
            return _BoundHistogram(self, self._key(labels))


class Family:
    """One metric family a pull collector emits at exposition time.

    Collectors snapshot subsystems that already keep their own exact
    counters (compile cache, serving, store) — ``Family`` is just the
    render-side container: ``fam.sample(value, **labels)``.
    """

    def __init__(self, kind: str, name: str, help_text: str = ""):
        self.kind = kind
        self.name = name
        self.help = help_text
        self.samples: list[tuple[dict, float]] = []

    def sample(self, value: float, **labels) -> "Family":
        self.samples.append((labels, float(value)))
        return self


class MetricsRegistry:
    """Lock-protected registry of push metrics + pull collectors."""

    def __init__(self, enabled: bool = True, trace_enabled: bool = True,
                 max_series: int = 1024, max_spans: int = 512,
                 trace_sample: float = 1.0):
        self.enabled = bool(enabled)
        self.trace_enabled = bool(enabled) and bool(trace_enabled)
        self.max_series = max(1, int(max_series))
        self.max_spans = max(1, int(max_spans))
        # Span-ledger sampling: the fraction of jobs whose span trees
        # persist, decided deterministically per request id
        # (obs/tracing.py new_trace).  Metrics are never sampled.
        self.trace_sample = min(1.0, max(0.0, float(trace_sample)))
        self.lock = make_lock("MetricsRegistry.lock")
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], Iterable[Family]]] = []
        #: SAMPLES routed to an overflow series (one per observation
        #: past the cap, not one per distinct combination — tracking
        #: dropped combinations would itself be unbounded state).
        self.series_overflows = 0

    # -- registration (idempotent by name) ------------------------------------

    def _get_or_make(self, cls, name, help_text, labels, **kw):
        with self.lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = cls(
                    self, name, help_text, labels, **kw
                )
            return metric

    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] | None = None) -> Histogram:
        return self._get_or_make(
            Histogram, name, help_text, labels, buckets=buckets
        )

    def add_collector(self, fn: Callable[[], Iterable[Family]]) -> None:
        """Register a pull collector: called at exposition time, must
        return Family objects and must be fast; exceptions degrade that
        collector's families only, never the exposition."""
        with self.lock:
            self._collectors.append(fn)

    def remove_collector(self, fn) -> None:
        with self.lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    # -- introspection --------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-shaped view of the push metrics (the legacy endpoints
        render from this): {name: {kind, series: [{labels, ...}]}}."""
        out: dict = {}
        with self.lock:
            for name, metric in self._metrics.items():
                series = []
                for key, state in metric._series.items():
                    entry: dict = {"labels": metric._labels_of(key)}
                    if metric.kind == "histogram":
                        entry.update(
                            count=state["count"],
                            sum=state["sum"],
                            buckets=dict(
                                zip(
                                    map(str, metric.buckets),
                                    state["counts"],
                                )
                            ),
                        )
                    else:
                        entry["value"] = state
                    series.append(entry)
                out[name] = {"kind": metric.kind, "series": series}
        return out

    def collect_all(self, names=None) -> list:
        """Unified sample view over push metrics AND pull collectors —
        the surface the rollup engine (obs/rollup.py) snapshots each
        tick.  Returns one dict per series::

            {"name", "kind", "labels": {...}, "value": float}        # scalar
            {"name", "kind": "histogram", "labels": {...},
             "edges": (...), "cum": (...), "sum": s, "count": n}     # cum
                                                                     # incl +Inf

        ``names`` (a set/sequence) filters to those families —
        collectors still all run (they emit whole family groups), but
        only matching samples return.  Histogram bucket counts come
        back CUMULATIVE (Prometheus ``le`` semantics) so windowed
        quantiles derive from plain point-to-point deltas."""
        if not self.enabled:
            return []
        wanted = set(names) if names is not None else None
        out: list = []
        with self.lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
            for metric in metrics:
                if wanted is not None and metric.name not in wanted:
                    continue
                for key, state in metric._series.items():
                    labels = metric._labels_of(key)
                    if metric.kind == "histogram":
                        cum, total = [], 0
                        for n in state["counts"]:
                            total += n
                            cum.append(total)
                        cum.append(state["count"])  # +Inf bucket
                        out.append({
                            "name": metric.name, "kind": "histogram",
                            "labels": labels,
                            "edges": metric.buckets,
                            "cum": tuple(cum),
                            "sum": state["sum"],
                            "count": state["count"],
                        })
                    else:
                        out.append({
                            "name": metric.name, "kind": metric.kind,
                            "labels": labels, "value": float(state),
                        })
        # Collectors run OUTSIDE the lock (same contract as
        # render_prometheus: exposition cost must never stall a
        # hot-path observe, and a collector may itself take locks).
        for collector in collectors:
            try:
                families = list(collector())
            except Exception:  # noqa: BLE001 — one bad collector must
                continue  # not take down the snapshot
            for fam in families:
                if wanted is not None and fam.name not in wanted:
                    continue
                for labels, value in fam.samples:
                    out.append({
                        "name": fam.name, "kind": fam.kind,
                        "labels": dict(labels), "value": float(value),
                    })
        return out

    # -- exposition -----------------------------------------------------------

    def _render_family(self, lines, kind, name, help_text, samples):
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        for labels, value in samples:
            lines.append(
                f"{name}{_labels_str(labels)} {_format_value(value)}"
            )

    def render_prometheus(self) -> str:
        """The full registry in Prometheus text exposition 0.0.4."""
        lines: list[str] = []
        if not self.enabled:
            lines.append(
                "# observability disabled (LO_TPU_OBS_ENABLED=0)"
            )
            return "\n".join(lines) + "\n"
        with self.lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
            overflows = self.series_overflows
            rendered: list[tuple] = []
            for metric in metrics:
                if metric.kind == "histogram":
                    for key, state in metric._series.items():
                        base = metric._labels_of(key)
                        cum = 0
                        bucket_samples = []
                        for edge, n in zip(
                            metric.buckets, state["counts"]
                        ):
                            cum += n
                            bucket_samples.append(
                                ({**base, "le": _format_value(edge)},
                                 cum)
                            )
                        bucket_samples.append(
                            ({**base, "le": "+Inf"}, state["count"])
                        )
                        rendered.append((
                            "histogram", metric.name, metric.help,
                            bucket_samples, base,
                            state["sum"], state["count"],
                        ))
                else:
                    samples = [
                        (metric._labels_of(key), value)
                        for key, value in metric._series.items()
                    ]
                    rendered.append((
                        metric.kind, metric.name, metric.help,
                        samples, None, None, None,
                    ))
        # Render OUTSIDE the lock: exposition cost must never stall a
        # hot-path observe().
        emitted_type: set[str] = set()
        for kind, name, help_text, samples, base, hsum, hcount in rendered:
            if kind == "histogram":
                if name not in emitted_type:
                    emitted_type.add(name)
                    if help_text:
                        lines.append(f"# HELP {name} {help_text}")
                    lines.append(f"# TYPE {name} histogram")
                for labels, value in samples:
                    lines.append(
                        f"{name}_bucket{_labels_str(labels)} "
                        f"{_format_value(value)}"
                    )
                lines.append(
                    f"{name}_sum{_labels_str(base)} "
                    f"{_format_value(hsum)}"
                )
                lines.append(
                    f"{name}_count{_labels_str(base)} "
                    f"{_format_value(hcount)}"
                )
            else:
                self._render_family(lines, kind, name, help_text, samples)
        for collector in collectors:
            try:
                families = list(collector())
            except Exception:  # noqa: BLE001 — one bad collector must
                continue  # not take down the exposition
            for fam in families:
                self._render_family(
                    lines, fam.kind, fam.name, fam.help, fam.samples
                )
        self._render_family(
            lines, "counter", "lo_obs_series_overflow_total",
            "Samples routed to an _overflow series because the metric "
            "was at LO_TPU_OBS_MAX_SERIES label combinations.",
            [({}, overflows)],
        )
        return "\n".join(lines) + "\n"


# -- process-wide singleton ---------------------------------------------------

_registry: MetricsRegistry | None = None
_registry_lock = make_lock("metrics._registry_lock")


def get_registry() -> MetricsRegistry:
    """The process-wide registry, sized from config (LO_TPU_OBS_*).

    Lock-free fast path: the singleton read is a single atomic load
    (hot-path instrumentation — HTTP dispatch, predict latency —
    resolves the registry per call), with the lock taken only to
    build it."""
    global _registry
    reg = _registry
    if reg is not None:
        return reg
    with _registry_lock:
        if _registry is None:
            from learningorchestra_tpu.config import get_config

            obs = get_config().obs
            _registry = MetricsRegistry(
                enabled=obs.enabled,
                trace_enabled=obs.trace,
                max_series=obs.max_series,
                max_spans=obs.max_spans,
                trace_sample=getattr(obs, "trace_sample", 1.0),
            )
        return _registry


def reset_registry(**overrides) -> MetricsRegistry:
    """Replace the singleton (tests; the bench's on/off overhead
    probe).  With overrides, builds directly from them; bare call
    rebuilds from config."""
    global _registry
    with _registry_lock:
        if overrides:
            _registry = MetricsRegistry(**overrides)
            return _registry
        _registry = None
    return get_registry()
