"""Debug-bundle assembler: capture-at-incident for the flight recorder.

When something pages — an SLO objective entering ``firing``
(SLOService.add_sink), a job failing with its preemption retries
exhausted, a deadline-watchdog expiry, a lock-witness stall, or an
operator's manual ``POST /observability/bundle`` — this module
snapshots everything a human needs to reconstruct the last 30 seconds
into one versioned on-disk directory:

- ``flight.json``   — every flight-recorder ring plus the merged
  incident timeline (obs/flight.py);
- ``metrics.json``  — the full metrics-registry snapshot;
- ``rollup.json``   — rollup engine status + ring tails per core
  family (the time dimension around the incident);
- ``slo.json``      — live alert states, transition history,
  objective status;
- ``fleet.json``    — the fleet snapshot including the autoscaler's
  decision ledger;
- ``journal.json``  — the newest job-journal records;
- ``faults.json``   — armed schedules + trigger counters;
- ``locks.json``    — the lock witness's edges/events/stalls;
- ``manifest.json`` — name, reason, trigger detail, file sizes,
  errors, and (knob-gated) the name of an auto-started short
  ``jax.profiler`` capture.

Durability discipline mirrors obs/profiling.py: assemble into a
hidden temp directory, then one atomic rename — a reader never sees a
half-written bundle.  Retention is bounded (oldest pruned), and auto
triggers are debounced + single-flight so an alert storm produces ONE
bundle, not fifty.  Content providers are injected by the API server
(obs/ must not import serve/ or jobs/); a missing or failing provider
degrades to an entry in ``manifest.errors``, never a lost bundle.

Knobs: ``LO_TPU_BUNDLE_*`` (config.py BundleConfig).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.log import get_logger, kv
from learningorchestra_tpu.obs import flight as obs_flight

logger = get_logger("bundle")

__all__ = [
    "BundleBusy",
    "BundleError",
    "BundleNotFound",
    "BundleService",
    "ensure_service",
    "get_service",
    "reset_service",
    "trigger",
]

#: Bundle layout version, stamped into every manifest.
BUNDLE_VERSION = 1

_NAME_RE = re.compile(r"[A-Za-z0-9_][A-Za-z0-9_.\-]*")
_SLUG_RE = re.compile(r"[^A-Za-z0-9_.\-]+")


class BundleError(Exception):
    """Bundle plane failure (maps to HTTP 406)."""


class BundleBusy(BundleError):
    """A bundle is already being assembled (maps to HTTP 409)."""


class BundleNotFound(BundleError):
    """No bundle by that name (maps to HTTP 404)."""


class BundleService:
    """Trigger-driven snapshot assembly + the on-disk bundle store.

    ``providers`` maps content-file stems to zero-arg callables
    returning JSON-serializable documents; the server injects the
    subsystems' views at construction.  ``profiler`` is the server's
    ProfilerService for the knob-gated auto capture.
    """

    def __init__(self, cfg, providers: dict | None = None,
                 profiler=None):
        self.cfg = cfg
        self.dir = cfg.dir or os.path.join(".", "_bundles")
        self.providers = dict(providers or {})
        self.profiler = profiler
        self._lock = make_lock("BundleService._lock")
        self._building = False
        self._last_auto: float | None = None
        self._seq = 0
        self.built = 0
        self.debounced = 0

    # -- triggers ------------------------------------------------------------

    def trigger(self, reason: str, detail: dict | None = None) -> str | None:
        """Auto-trigger path (SLO sink, job engine, watchdogs):
        debounced and single-flight, assembled on a daemon thread so a
        rollup tick or an engine worker never blocks on file IO.
        Returns the bundle name it started, or None (disabled,
        debounced, or already building)."""
        if not self.cfg.enabled:
            return None
        now = time.monotonic()
        with self._lock:
            if self._building:
                self.debounced += 1
                return None
            if (
                self._last_auto is not None
                and now - self._last_auto < self.cfg.debounce_s
            ):
                self.debounced += 1
                return None
            self._last_auto = now
            self._building = True
            name = self._next_name_locked(reason)
        threading.Thread(
            target=self._assemble_and_release,
            args=(name, reason, detail),
            name="bundle-assemble", daemon=True,
        ).start()
        return name

    def build(self, reason: str, detail: dict | None = None) -> dict:
        """Manual path (POST /observability/bundle): synchronous, no
        debounce — an operator asking for evidence gets it — but still
        single-flight (a concurrent build raises BundleBusy)."""
        with self._lock:
            if self._building:
                raise BundleBusy(
                    "a bundle is already being assembled"
                )
            self._building = True
            name = self._next_name_locked(reason)
        try:
            return self._assemble(name, reason, detail)
        finally:
            with self._lock:
                self._building = False

    def _assemble_and_release(self, name, reason, detail) -> None:
        try:
            self._assemble(name, reason, detail)
        except Exception:  # noqa: BLE001 — a failed capture must
            logger.exception("bundle assembly failed")  # never crash
        finally:  # the triggering thread's caller
            with self._lock:
                self._building = False

    def _next_name_locked(self, reason: str) -> str:
        self._seq += 1
        slug = _SLUG_RE.sub("-", reason).strip("-.") or "manual"
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        return f"{stamp}-{self._seq:03d}-{slug}"[:80]

    # -- assembly ------------------------------------------------------------

    def _assemble(self, name: str, reason: str,
                  detail: dict | None) -> dict:
        """Snapshot every source into ``<dir>/.tmp-<name>``, write the
        manifest, rename atomically, prune retention.  Returns the
        manifest."""
        os.makedirs(self.dir, exist_ok=True)
        tmp = os.path.join(self.dir, f".tmp-{name}")
        final = os.path.join(self.dir, name)
        os.makedirs(tmp, exist_ok=True)
        errors: dict = {}
        files: list = []

        def write(stem: str, doc) -> None:
            data = json.dumps(doc, default=str, indent=1).encode()
            path = os.path.join(tmp, f"{stem}.json")
            with open(path, "wb") as fh:
                fh.write(data)
            files.append({"name": f"{stem}.json", "bytes": len(data)})

        # The flight rings are the bundle's reason to exist — captured
        # first, before slower providers age them.
        try:
            write("flight", {
                "snapshot": obs_flight.snapshot(),
                "timeline": obs_flight.timeline(),
            })
        except Exception as exc:  # noqa: BLE001
            errors["flight"] = repr(exc)
        for stem, provider in self.providers.items():
            try:
                write(stem, provider())
            except Exception as exc:  # noqa: BLE001 — one broken
                errors[stem] = repr(exc)  # source, not a lost bundle
        capture = self._maybe_profile(name)
        manifest = {
            "name": name,
            "version": BUNDLE_VERSION,
            "reason": reason,
            "detail": detail or {},
            "createdAt": time.time(),
            "files": files,
            "errors": errors,
            "profileCapture": capture,
        }
        data = json.dumps(manifest, default=str, indent=1).encode()
        with open(os.path.join(tmp, "manifest.json"), "wb") as fh:
            fh.write(data)
        try:
            os.rename(tmp, final)
        except OSError as exc:
            shutil.rmtree(tmp, ignore_errors=True)
            raise BundleError(
                f"could not publish bundle {name!r}: {exc}"
            ) from None
        with self._lock:
            self.built += 1
        logger.info(kv(
            event="bundle_built", name=name, reason=reason,
            files=len(files), errors=len(errors),
        ))
        self._prune()
        return manifest

    def _maybe_profile(self, name: str) -> str | None:
        """Knob-gated short jax.profiler capture riding the bundle:
        start with an auto-stop deadline and record the capture name —
        the profiler's own store retains the artifacts.  A busy
        profiler (ProfilerConflict) or any failure degrades to None."""
        if not self.cfg.profile or self.profiler is None:
            return None
        try:
            doc = self.profiler.start(
                name=f"bundle-{name}"[:60],
                max_seconds=self.cfg.profile_s,
            )
            return doc.get("name")
        except Exception as exc:  # noqa: BLE001 — includes
            logger.warning(kv(  # ProfilerConflict: capture in flight
                event="bundle_profile_skipped", error=repr(exc),
            ))
            return None

    def _prune(self) -> None:
        keep = max(1, int(self.cfg.max_bundles))
        names = self._names()
        for victim in names[: max(0, len(names) - keep)]:
            try:
                shutil.rmtree(os.path.join(self.dir, victim))
            except OSError:
                pass

    # -- store views ---------------------------------------------------------

    def _names(self) -> list:
        """Completed bundle names, oldest first (names sort by their
        UTC stamp + sequence prefix)."""
        try:
            entries = os.listdir(self.dir)
        except OSError:
            return []
        return sorted(
            e for e in entries
            if not e.startswith(".")
            and os.path.isfile(
                os.path.join(self.dir, e, "manifest.json")
            )
        )

    def manifest(self, name: str) -> dict | None:
        if not _NAME_RE.fullmatch(name):
            return None
        try:
            with open(
                os.path.join(self.dir, name, "manifest.json"), "rb"
            ) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def list_bundles(self) -> list:
        out = []
        for name in self._names():
            doc = self.manifest(name)
            if doc is not None:
                out.append({
                    "name": doc.get("name", name),
                    "reason": doc.get("reason"),
                    "createdAt": doc.get("createdAt"),
                    "files": len(doc.get("files", [])),
                    "profileCapture": doc.get("profileCapture"),
                })
        return out

    def read_file(self, name: str, rel: str) -> bytes:
        """One bundle artifact's bytes; rejects names/paths that
        escape the bundle directory (same guard as the profiler's
        read_file)."""
        if not _NAME_RE.fullmatch(name):
            raise BundleNotFound(f"no bundle {name!r}")
        root = os.path.realpath(os.path.join(self.dir, name))
        path = os.path.realpath(os.path.join(root, rel))
        if path != root and not path.startswith(root + os.sep):
            raise BundleError(
                f"path {rel!r} escapes the bundle directory"
            )
        try:
            with open(path, "rb") as fh:
                return fh.read()
        except OSError:
            raise BundleNotFound(
                f"no file {rel!r} in bundle {name!r}"
            ) from None

    def delete(self, name: str) -> bool:
        if not _NAME_RE.fullmatch(name):
            return False
        path = os.path.join(self.dir, name)
        if not os.path.isdir(path):
            return False
        shutil.rmtree(path, ignore_errors=True)
        return True

    def delete_all(self) -> int:
        n = 0
        for name in self._names():
            if self.delete(name):
                n += 1
        return n

    def status(self) -> dict:
        with self._lock:
            building = self._building
            built = self.built
            debounced = self.debounced
        return {
            "enabled": self.cfg.enabled,
            "dir": self.dir,
            "building": building,
            "built": built,
            "debounced": debounced,
            "maxBundles": self.cfg.max_bundles,
            "debounceS": self.cfg.debounce_s,
            "bundles": self.list_bundles(),
        }


# -- process-wide singleton ---------------------------------------------------

_service: BundleService | None = None
_service_lock = make_lock("bundle._service_lock")


def get_service() -> BundleService | None:
    """The configured singleton, or None — unlike the sibling obs
    planes, a bundle service never self-constructs: its content
    providers only exist once an API server wires them."""
    with _service_lock:
        return _service


def ensure_service(cfg, providers: dict | None = None,
                   profiler=None) -> BundleService:
    """Build the singleton if none exists yet (API-server
    construction), then return it."""
    global _service
    with _service_lock:
        if _service is None:
            _service = BundleService(
                cfg, providers=providers, profiler=profiler
            )
        return _service


def reset_service(cfg=None, providers: dict | None = None,
                  profiler=None) -> BundleService | None:
    """Replace the singleton (tests)."""
    global _service
    with _service_lock:
        _service = None if cfg is None else BundleService(
            cfg, providers=providers, profiler=profiler
        )
        return _service


def trigger(reason: str, **detail) -> str | None:
    """Module-level auto-trigger for subsystems that must not hold a
    server reference (jobs/engine.py, concurrency_rt.py): forwards to
    the singleton when one is configured, else a no-op."""
    with _service_lock:
        service = _service
    if service is None:
        return None
    try:
        return service.trigger(reason, detail or None)
    except Exception:  # noqa: BLE001 — a broken assembler must never
        logger.exception("bundle trigger failed")  # break its caller
        return None
