"""Always-on flight recorder: the last N events of runtime truth.

The metrics/rollup/SLO planes (obs/metrics.py, obs/rollup.py,
obs/slo.py) can say THAT something went wrong — a p99 breach, a burn
rate over threshold — but by the time a human looks, the per-request
and per-step evidence explaining WHY is gone.  This module keeps it:
bounded, lock-cheap per-domain event rings recording

- ``http``    — one event per completed request (route, status,
  latency, request id);
- ``decode``  — per-stream lifecycle on the streaming LM engine
  (admit, pool grow, TTFT, abort, step errors);
- ``jobs``    — engine dispatch / preempt-retry / fence / terminal
  decisions;
- ``compile`` — compiled-program builds and AOT restores;
- ``faults``  — every fault-point trigger the chaos plane fires;
- ``locks``   — lock-witness contention waits and stall-watchdog
  dumps;
- ``cluster`` — control-plane claim/renew/steal/fence-refused/
  quota-reject decisions (jobs/cluster.py), each with the engine id
  and epoch — a partition incident reads as one merged timeline.

Every event is stamped with ``t`` (``time.monotonic()``), ``wall``
(``time.time()``) and — when one is bound on the calling thread — the
``requestId`` from obs/tracing.py, so ``timeline()`` can merge the
rings into one ordered incident narrative ("request R hit route X,
tripped fault point Y, job Z preempted, lock W stalled").

Hot-path contract: ``record()`` takes NO locks.  Rings are
``collections.deque(maxlen=N)`` — appends are atomic under the GIL —
and the disabled path is a single module-global check, so the recorder
rides every dispatch at well under 1% of a single-row batcher dispatch
(bench.py ``_flight_probe`` banks the numbers).  ``configure()`` /
``snapshot()`` mutate/read module state under a witnessed lock; a
snapshot copies each ring (``list(deque)`` is also GIL-atomic) so
readers never observe a half-written event.

Knobs: ``LO_TPU_FLIGHT_*`` (config.py FlightConfig).
"""

from __future__ import annotations

import collections
import time

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.obs import tracing as obs_tracing

__all__ = [
    "DOMAINS",
    "configure",
    "enabled",
    "ensure",
    "record",
    "reset",
    "snapshot",
    "status",
    "timeline",
]

#: The fixed domain set — one bounded ring each.  Adding a domain is a
#: code change on purpose: rings are capacity planning, not a dict that
#: grows per caller typo.
DOMAINS = (
    "http", "decode", "jobs", "compile", "faults", "locks", "cluster",
)

_lock = make_lock("flight._lock")
#: None while disabled (the record() fast path is this one check);
#: {domain: deque} while enabled.
_rings: dict | None = None
_events_per_ring = 0


def record(domain: str, kind: str, **fields) -> None:
    """Append one event to ``domain``'s ring.  Lock-free: a module
    read, a dict lookup and a GIL-atomic deque append.  Unknown
    domains are dropped (never raise on the hot path)."""
    rings = _rings
    if rings is None:
        return
    ring = rings.get(domain)
    if ring is None:
        return
    event = {
        "t": time.monotonic(),
        "wall": time.time(),
        "kind": kind,
    }
    rid = obs_tracing.get_request_id()
    if rid:
        event["requestId"] = rid
    if fields:
        event.update(fields)
    ring.append(event)


def enabled() -> bool:
    return _rings is not None


def configure(cfg) -> None:
    """Arm (or disarm) the recorder from a FlightConfig.  Existing
    ring contents are dropped — configuration marks a new epoch."""
    global _rings, _events_per_ring
    with _lock:
        if not cfg.enabled or cfg.events <= 0:
            _rings = None
            _events_per_ring = 0
            return
        _events_per_ring = int(cfg.events)
        _rings = {
            domain: collections.deque(maxlen=_events_per_ring)
            for domain in DOMAINS
        }


def ensure(cfg) -> None:
    """Arm from ``cfg`` only if never configured (API-server boot:
    a test that armed a custom recorder first wins, matching the
    ensure_* singleton idiom of the sibling obs modules)."""
    with _lock:
        already = _rings is not None or _events_per_ring != 0
    if not already:
        configure(cfg)


def reset(cfg=None) -> None:
    """Tests/bench: drop all state; re-arm when ``cfg`` is given."""
    global _rings, _events_per_ring
    with _lock:
        _rings = None
        _events_per_ring = 0
    if cfg is not None:
        configure(cfg)


def snapshot(domains=None, limit: int = 0) -> dict:
    """Point-in-time copy of the rings: ``{"enabled", "events":
    {domain: [event, ...]}}`` oldest-first, optionally filtered to
    ``domains`` and truncated to the newest ``limit`` per ring."""
    rings = _rings
    doc: dict = {
        "enabled": rings is not None,
        "eventsPerRing": _events_per_ring,
        "events": {},
    }
    if rings is None:
        return doc
    for domain, ring in rings.items():
        if domains and domain not in domains:
            continue
        events = list(ring)  # GIL-atomic copy of the whole ring
        if limit > 0:
            events = events[-limit:]
        doc["events"][domain] = events
    return doc


def timeline(domains=None, limit: int = 0) -> list:
    """The merged incident timeline: every ring's events in one list
    ordered by monotonic ``t`` (newest last), each tagged with its
    ``domain``.  ``limit`` keeps the newest N after the merge."""
    snap = snapshot(domains=domains)
    merged = [
        {**event, "domain": domain}
        for domain, events in snap["events"].items()
        for event in events
    ]
    merged.sort(key=lambda event: event["t"])
    if limit > 0:
        merged = merged[-limit:]
    return merged


def status() -> dict:
    """Ring occupancy without copying event payloads."""
    rings = _rings
    return {
        "enabled": rings is not None,
        "eventsPerRing": _events_per_ring,
        "rings": {
            domain: len(ring) for domain, ring in rings.items()
        } if rings is not None else {},
    }
