"""Cost-accounting plane: per-program FLOPs/HBM ledgers and
device-time attribution.

The obs layer (metrics.py, tracing.py) answers *how often* and *how
long* things run; nothing answered *what the hardware is doing*: no
per-program FLOPs or bytes, no HBM footprint, no way to say which job
or served model consumed the device seconds, no achieved-vs-peak
utilization.  That visibility is the precondition the pjit/TPUv4
scaling work treats as table stakes for capacity planning (PAPERS.md)
— and it closes a standing debt: the compiled-program cache's byte cap
charged a flat 32 MiB per entry because nothing ever measured one.

Two ledgers, both process-wide singletons sized from config
(``LO_TPU_COSTS_*``):

- :class:`CostLedger` — one :class:`ProgramCost` per compiled-program
  fingerprint.  Builders with example arguments in hand call
  :func:`analyze_jitted`, which lowers the jitted callable against
  shape avatars and reads XLA's own numbers: ``Lowered.cost_analysis``
  (flops, bytes accessed — no backend compile needed) and, when
  ``deep`` analysis is on, an AOT ``compile()`` for
  ``Compiled.memory_analysis()`` (argument/output/temp/generated-code
  bytes — the HBM footprint) plus the serialized executable size.
  Backends that report nothing (CPU leaves several fields zero)
  degrade field-by-field, never fail a build.  The compile cache calls
  :func:`note_build` on EVERY build, so every entry exists even when
  no builder could analyze it, and charges the measured serialized
  size against its byte cap instead of the flat estimate.

- :class:`DeviceTimeLedger` — sampled per-dispatch attribution.
  Dispatch sites (the train epoch loop, the serving batcher dispatch)
  call :func:`attribute` with the elapsed device interval and the
  program's cost record; the ledger accumulates device seconds, flops
  and bytes per job (bounded ring), per served model and per
  (model, bucket), from which model-FLOPs-utilization (MFU) is
  ``flops / (device_s * peak_flops)`` when the operator configured the
  chip's peak (``LO_TPU_COSTS_PEAK_FLOPS``; unknown peak reports no
  MFU rather than a fabricated one).  ``LO_TPU_COSTS_SAMPLE`` thins
  the hook deterministically (every k-th dispatch, contributions
  scaled by k) so a microsecond-dispatch workload can dial the
  bookkeeping arbitrarily far down.

Everything here is disabled by ``LO_TPU_COSTS_ENABLED=0``: probes
return immediately and builders skip analysis — the bench's
``_costs_probe`` measures exactly that delta.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import math
import time
from collections import OrderedDict

from learningorchestra_tpu.concurrency_rt import make_lock

__all__ = [
    "CostLedger",
    "DeviceTimeLedger",
    "ProgramCost",
    "analyze_jitted",
    "attribute",
    "current_job",
    "devtime",
    "enabled",
    "get_ledger",
    "job_scope",
    "job_summary",
    "mfu",
    "note_build",
    "reset",
    "serialized_bytes",
    "serving_totals",
]


@dataclasses.dataclass
class ProgramCost:
    """What ONE execution of a compiled program costs, as XLA reports
    it.  ``None`` fields mean "the backend reported nothing" — never
    fabricated."""

    key: str
    label: str = ""
    flops: float | None = None
    bytes_accessed: float | None = None
    argument_bytes: int | None = None
    output_bytes: int | None = None
    temp_bytes: int | None = None
    generated_code_bytes: int | None = None
    serialized_bytes: int | None = None
    built_s: float = 0.0
    builds: int = 0
    analyzed: bool = False
    # True when the analyzed lowering is collective-free by
    # construction (single-device MPMD stage programs, host-avatar
    # serve probes): the flops/bytes are pure compute, so MFU and
    # lo_serving_bucket_* derived from them stay honest for multi-chip
    # programs — a whole-mesh lowering's collective FLOPs would
    # inflate both.
    collectives_excluded: bool = False
    created_at: float = dataclasses.field(default_factory=time.time)

    @property
    def peak_bytes(self) -> int | None:
        """Approximate peak HBM while this program runs: arguments +
        outputs + XLA temporaries + code."""
        parts = [self.argument_bytes, self.output_bytes,
                 self.temp_bytes, self.generated_code_bytes]
        known = [p for p in parts if p is not None]
        return sum(known) if known else None

    def to_doc(self) -> dict:
        return {
            "key": self.key[:12],
            "label": self.label,
            "flops": self.flops,
            "bytesAccessed": self.bytes_accessed,
            "argumentBytes": self.argument_bytes,
            "outputBytes": self.output_bytes,
            "tempBytes": self.temp_bytes,
            "generatedCodeBytes": self.generated_code_bytes,
            "peakBytes": self.peak_bytes,
            "serializedBytes": self.serialized_bytes,
            "builtS": round(self.built_s, 4),
            "builds": self.builds,
            "analyzed": self.analyzed,
            "collectivesExcluded": self.collectives_excluded,
        }


class CostLedger:
    """Bounded per-fingerprint ProgramCost map (LRU on insertion): a
    process that builds unbounded program diversity must not grow this
    without limit — evicted records simply fall back to flat byte
    charges if their cache entry is ever re-inserted."""

    def __init__(self, max_programs: int = 256):
        self.max_programs = max(1, int(max_programs))
        self._lock = make_lock("CostLedger._lock")
        self._programs: OrderedDict[str, ProgramCost] = OrderedDict()
        self.analyses = 0
        self.analysis_failures = 0
        self.analysis_time_s = 0.0

    def _entry_locked(self, key: str, label: str) -> ProgramCost:
        cost = self._programs.get(key)
        if cost is None:
            cost = self._programs[key] = ProgramCost(key=key)
            while len(self._programs) > self.max_programs:
                self._programs.popitem(last=False)
        if label and not cost.label:
            cost.label = label
        return cost

    def note_build(self, key: str, label: str | None,
                   built_s: float) -> ProgramCost:
        """Called by the compile cache on EVERY build: guarantees a
        ledger entry per built program (analyzed or not) and keeps the
        per-program build time current."""
        with self._lock:
            cost = self._entry_locked(key, label or "")
            cost.builds += 1
            cost.built_s = float(built_s)
            return cost

    def record_analysis(self, key: str, label: str | None, *,
                        flops=None, bytes_accessed=None, memory=None,
                        serialized=None, analysis_s: float = 0.0,
                        collectives_excluded: bool = False
                        ) -> ProgramCost:
        with self._lock:
            cost = self._entry_locked(key, label or "")
            if collectives_excluded:
                cost.collectives_excluded = True
            if flops is not None:
                cost.flops = float(flops)
            if bytes_accessed is not None:
                cost.bytes_accessed = float(bytes_accessed)
            if memory is not None:
                # Field-by-field: a backend omitting an attribute
                # leaves the field None (unreported), never a
                # fabricated 0.
                def _mem(attr):
                    value = getattr(memory, attr, None)
                    return int(value) if value is not None else None

                cost.argument_bytes = _mem("argument_size_in_bytes")
                cost.output_bytes = _mem("output_size_in_bytes")
                cost.temp_bytes = _mem("temp_size_in_bytes")
                cost.generated_code_bytes = _mem(
                    "generated_code_size_in_bytes"
                )
            if serialized is not None:
                cost.serialized_bytes = int(serialized)
            cost.analyzed = True
            self.analyses += 1
            self.analysis_time_s += float(analysis_s)
            return cost

    def note_failure(self) -> None:
        with self._lock:
            self.analysis_failures += 1

    def get(self, key: str) -> ProgramCost | None:
        with self._lock:
            return self._programs.get(key)

    def serialized_bytes(self, key: str) -> int | None:
        with self._lock:
            cost = self._programs.get(key)
        if cost is None:
            return None
        return cost.serialized_bytes

    def snapshot(self) -> dict:
        with self._lock:
            programs = [c.to_doc() for c in self._programs.values()]
            return {
                "programs": programs,
                "maxPrograms": self.max_programs,
                "analyses": self.analyses,
                "analysisFailures": self.analysis_failures,
                "analysisTimeS": round(self.analysis_time_s, 4),
            }


class DeviceTimeLedger:
    """Sampled device-time attribution: who consumed the device.

    ``attribute`` accumulates (device seconds, flops, bytes,
    dispatches) per job — a bounded insertion-ordered ring, so a
    long-lived server keeps the freshest N jobs — per served model,
    and per (model, bucket).  All counters are scaled by the sampling
    weight, so thinned recording stays an unbiased estimate."""

    def __init__(self, max_jobs: int = 64, sample: float = 1.0,
                 max_models: int = 64):
        self.max_jobs = max(1, int(max_jobs))
        self.max_models = max(1, int(max_models))
        self.sample = min(1.0, max(0.0, float(sample)))
        # Every k-th dispatch records, contributions scaled by k —
        # deterministic (drills reproduce) and unbiased in the mean.
        # The rate QUANTIZES to 1/round(1/sample): only 1, 1/2, 1/3,
        # ... are representable — e.g. 0.7 records at full rate, 0.4
        # records 1-in-2 (the config knob documents this).
        self._stride = (
            max(1, round(1.0 / self.sample)) if self.sample > 0 else 0
        )
        self._lock = make_lock("DeviceTimeLedger._lock")
        # PER-KEY stride counters (bounded ring): one global counter
        # would alias deterministic interleavings — two models whose
        # dispatches strictly alternate at stride 2 would leave one
        # of them never sampled and the other double-counted.  Keyed
        # by the attribution entity (model or job), each stream thins
        # independently and stays unbiased.
        self._counters: OrderedDict[str, int] = OrderedDict()
        # Entries are 4-slot lists [device_s, flops, bytes,
        # dispatches], not dicts: record() sits on the serving
        # dispatch hot path and list indexing keeps the recorded hit
        # ~1 µs — the bench's _costs_probe pins the number.  Jobs AND
        # models ride bounded freshest-N rings (a multi-tenant server
        # churning model names must not grow these — or the per-model
        # metric cardinality — without limit); a model's bucket
        # entries die with it.
        self._jobs: OrderedDict[str, list] = OrderedDict()
        self._models: OrderedDict[str, list] = OrderedDict()
        self._buckets: dict[tuple, list] = {}
        self._totals = [0.0, 0.0, 0.0, 0]

    def will_record(self, key: str = "") -> int:
        """Advance ``key``'s sampling stride (the model or job being
        attributed): the weight to record this dispatch with, or 0
        (sampled out) — callers skip the device sync entirely for a
        0, which is what keeps a thinned hook off the dispatch
        pipeline."""
        stride = self._stride
        if stride == 1:
            return 1  # full rate: no counter, no lock
        if stride == 0:
            return 0
        with self._lock:
            n = self._counters.get(key)
            if n is None:
                n = 0
                while len(self._counters) >= 4 * self.max_models:
                    self._counters.popitem(last=False)
            n += 1
            self._counters[key] = n
            # LRU, not FIFO: a hot stream's counter must outlive
            # one-shot stale keys, or churny job names would keep
            # resetting its stride phase.
            self._counters.move_to_end(key)
            return stride if n % stride == 0 else 0

    def _model_entry_locked(self, model: str) -> list:
        """The model's accumulator, evicting the OLDEST model (and
        cascading its bucket entries) past the cap.  Caller holds the
        lock."""
        entry = self._models.get(model)
        if entry is None:
            entry = self._models[model] = [0.0, 0.0, 0.0, 0]
            while len(self._models) > self.max_models:
                evicted, _ = self._models.popitem(last=False)
                for bkey in [
                    k for k in self._buckets if k[0] == evicted
                ]:
                    del self._buckets[bkey]
        return entry

    def record_model(self, weight, duration_s, flops, nbytes, model,
                     bucket) -> None:
        """Positional fast path for the serving dispatch hook (no
        kwargs parsing, no job branch) — the bench's _costs_probe
        pins this exact call at <1% of a serving dispatch, which is
        why the accumulate blocks stay hand-inlined here."""
        d = duration_s * weight
        f = (flops or 0.0) * weight
        b = (nbytes or 0.0) * weight
        with self._lock:
            t = self._totals
            t[0] += d
            t[1] += f
            t[2] += b
            t[3] += weight
            entry = self._model_entry_locked(model)
            entry[0] += d
            entry[1] += f
            entry[2] += b
            entry[3] += weight
            if bucket is not None:
                bkey = (model, bucket)
                entry = self._buckets.get(bkey)
                if entry is None:
                    entry = self._buckets[bkey] = [0.0, 0.0, 0.0, 0]
                entry[0] += d
                entry[1] += f
                entry[2] += b
                entry[3] += weight

    def record(self, weight: int, duration_s: float, *, flops=None,
               nbytes=None, job: str | None = None,
               model: str | None = None,
               bucket: int | None = None) -> None:
        """General form (not the serving hot path): totals + any of
        job/model/bucket.  The model/bucket half delegates to
        :meth:`record_model` so the eviction cascade exists once."""
        if model:
            self.record_model(
                weight, duration_s, flops, nbytes, model, bucket
            )
            if not job:
                return
            totals = None  # record_model already added them
        else:
            totals = self._totals
        d = duration_s * weight
        f = (flops or 0.0) * weight
        b = (nbytes or 0.0) * weight
        with self._lock:
            if totals is not None:
                totals[0] += d
                totals[1] += f
                totals[2] += b
                totals[3] += weight
            if job:
                entry = self._jobs.get(job)
                if entry is None:
                    entry = self._jobs[job] = [0.0, 0.0, 0.0, 0]
                    while len(self._jobs) > self.max_jobs:
                        self._jobs.popitem(last=False)
                entry[0] += d
                entry[1] += f
                entry[2] += b
                entry[3] += weight

    def attribute(self, duration_s: float, *, flops=None, nbytes=None,
                  job: str | None = None, model: str | None = None,
                  bucket: int | None = None) -> bool:
        """One-shot form (the train epoch loop, which is already
        synced): sampling decision + record in one call; returns
        whether it recorded."""
        weight = self.will_record(model or job or "")
        if not weight:
            return False
        self.record(
            weight, duration_s, flops=flops, nbytes=nbytes,
            job=job, model=model, bucket=bucket,
        )
        return True

    @staticmethod
    def _doc(entry: list, peak_flops: float) -> dict:
        doc = {
            "deviceTimeS": round(entry[0], 6),
            "flops": entry[1],
            "bytes": entry[2],
            "dispatches": entry[3],
        }
        util = mfu(entry[1], entry[0], peak_flops=peak_flops)
        if util is not None:
            doc["mfu"] = util
        return doc

    def model_device_s(self, model: str) -> float:
        """Accumulated device-seconds attributed to ``model`` (0.0
        when unseen/evicted) — the fleet autoscaler's cost-aware
        scale-up signal reads this as a monotone counter and takes
        deltas per tick."""
        with self._lock:
            entry = self._models.get(model)
            return float(entry[0]) if entry else 0.0

    def job_summary(self, job: str,
                    peak_flops: float = 0.0) -> dict | None:
        with self._lock:
            entry = self._jobs.get(job)
            entry = list(entry) if entry else None
        return self._doc(entry, peak_flops) if entry else None

    def snapshot(self, peak_flops: float = 0.0) -> dict:
        with self._lock:
            jobs = {k: list(v) for k, v in self._jobs.items()}
            models = {k: list(v) for k, v in self._models.items()}
            buckets = {k: list(v) for k, v in self._buckets.items()}
            totals = list(self._totals)
        return {
            "sample": self.sample,
            "totals": self._doc(totals, peak_flops),
            "jobs": {k: self._doc(v, peak_flops)
                     for k, v in jobs.items()},
            "models": {k: self._doc(v, peak_flops)
                       for k, v in models.items()},
            "buckets": {
                f"{m}:{b}": self._doc(v, peak_flops)
                for (m, b), v in sorted(buckets.items())
            },
        }


def mfu(flops: float, device_s: float, *,
        peak_flops: float) -> float | None:
    """Model-FLOPs-utilization: achieved over peak.  None when the
    peak is unconfigured or nothing ran — no fabricated utilization."""
    if peak_flops <= 0 or device_s <= 0 or flops <= 0:
        return None
    value = flops / (device_s * peak_flops)
    if not math.isfinite(value):
        return None
    # Significant digits, not decimal places: a tiny model on a big
    # chip legitimately runs at 1e-8 MFU and must not round to zero.
    return float(f"{value:.4g}")


# -- process-wide singletons --------------------------------------------------

_lock = make_lock("costs._lock")
_ledger: CostLedger | None = None
_devtime: DeviceTimeLedger | None = None
_cfg_cache = None


def _cfg():
    global _cfg_cache
    if _cfg_cache is None:
        from learningorchestra_tpu.config import get_config

        _cfg_cache = get_config().costs
    return _cfg_cache


def enabled() -> bool:
    return _cfg().enabled


def deep_enabled() -> bool:
    return _cfg().enabled and _cfg().deep


def peak_flops() -> float:
    return float(_cfg().peak_flops)


def get_ledger() -> CostLedger:
    global _ledger
    with _lock:
        if _ledger is None:
            _ledger = CostLedger(max_programs=_cfg().max_programs)
        return _ledger


def devtime() -> DeviceTimeLedger:
    global _devtime
    with _lock:
        if _devtime is None:
            cfg = _cfg()
            _devtime = DeviceTimeLedger(
                max_jobs=cfg.max_jobs, sample=cfg.sample
            )
        return _devtime


def reset(config=None) -> None:
    """Drop both ledgers (tests; config swap).  ``config`` overrides
    the CostsConfig the rebuilt singletons size from."""
    global _ledger, _devtime, _cfg_cache
    with _lock:
        _ledger = None
        _devtime = None
        _cfg_cache = config


# -- the compile-cache hooks --------------------------------------------------


def note_build(key: str, label: str | None, built_s: float) -> None:
    """Every compile-cache build lands here (see
    ``CompiledProgramCache.get_or_build``): the ledger entry exists
    from this moment even if no builder could run an analysis."""
    if not enabled():
        return
    get_ledger().note_build(key, label, built_s)


def serialized_bytes(key: str) -> int | None:
    """Measured executable size for the cache's byte cap, or None →
    the cache falls back to its flat per-entry estimate."""
    if not enabled():
        return None
    return get_ledger().serialized_bytes(key)


def _avatar(leaf):
    """Shape/dtype avatar of one example leaf, dtype-canonicalized:
    a float64 numpy example must lower as the float32 the real
    ``jnp.asarray`` call would produce under x64-disabled jax, or the
    probed program would not be the one that runs."""
    import jax
    import numpy as np

    if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
        leaf = np.asarray(leaf)
    try:
        dtype = jax.dtypes.canonicalize_dtype(leaf.dtype)
    except Exception:  # noqa: BLE001 — e.g. typed PRNG key dtypes
        dtype = leaf.dtype
    return jax.ShapeDtypeStruct(tuple(leaf.shape), dtype)


def _flatten_cost_analysis(raw):
    """Normalize ``cost_analysis()`` across jax versions: a dict, or a
    list of per-partition dicts (summed)."""
    if raw is None:
        return None
    if isinstance(raw, dict):
        return raw
    if isinstance(raw, (list, tuple)) and raw:
        merged: dict = {}
        for part in raw:
            if not isinstance(part, dict):
                return None
            for k, v in part.items():
                try:
                    merged[k] = merged.get(k, 0.0) + float(v)
                except (TypeError, ValueError):
                    continue
        return merged
    return None


def analyze_jitted(key: str, label: str | None, fn,
                   example_args: tuple, *,
                   aot_eligible: bool = True,
                   collectives_excluded: bool = False
                   ) -> ProgramCost | None:
    """Run XLA cost (and, deep, memory/size) analysis for the program
    ``fn(*example_args)`` and record it under ``key``.

    ``example_args`` may be real arrays or anything with shape/dtype —
    they are reduced to ShapeDtypeStruct avatars, so nothing touches
    (or donates) real buffers.  The lowering re-traces the function
    (~the cost of the trace the build already paid); the deep AOT
    ``compile()`` pays an XLA compile that the persistent XLA disk
    cache dedups against the first real call's.  Best-effort by
    design: any failure counts in ``analysis_failures`` and the build
    proceeds with the un-analyzed ledger entry.

    The deep path's serialized payload is the SAME artifact the
    durable warm-start store persists (train/aot_store.py), so when
    that store is enabled the payload is offered to it here — one
    serialize, two consumers.  ``aot_eligible=False`` opts a program
    out (tuple-valued builders: a restored single executable could not
    stand in for the (epoch, evaluate) pair consumers unpack)."""
    if not enabled():
        return None
    ledger = get_ledger()
    existing = ledger.get(key)
    if existing is not None and existing.analyzed:
        return existing  # device-set invalidation rebuilt it: costs hold
    t0 = time.perf_counter()
    payload = None
    try:
        import jax

        avatars = jax.tree_util.tree_map(_avatar, tuple(example_args))
        lowered = fn.lower(*avatars)
        cost = _flatten_cost_analysis(lowered.cost_analysis())
        memory = None
        serialized = None
        if deep_enabled():
            compiled = lowered.compile()
            try:
                memory = compiled.memory_analysis()
            except Exception:  # noqa: BLE001 — backend may not report
                memory = None
            payload = _serialize_payload(compiled)
            serialized = (
                len(payload[0]) if payload is not None
                else _hlo_proto_size(compiled)
            )
            if cost is None:
                cost = _flatten_cost_analysis(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 — analysis must never fail a build
        ledger.note_failure()
        return None
    record = ledger.record_analysis(
        key, label,
        flops=(cost or {}).get("flops"),
        bytes_accessed=(cost or {}).get("bytes accessed"),
        memory=memory,
        serialized=serialized,
        analysis_s=time.perf_counter() - t0,
        collectives_excluded=collectives_excluded,
    )
    if aot_eligible and payload is not None:
        _offer_aot(key, label, payload)
    return record


def _offer_aot(key: str, label: str | None, payload) -> None:
    """Hand the just-serialized executable to the durable store
    (disabled → one attribute check).  The store swallows its own
    failures; this guard covers import/config breakage."""
    try:
        from learningorchestra_tpu.train import aot_store

        store = aot_store.get_store()
        if store is not None:
            store.offer(key, payload, label=label)
    except Exception:  # noqa: BLE001 — persistence never fails a build
        pass


def _serialize_payload(compiled):
    """The full ``serialize_executable`` payload tuple — blob plus the
    in/out tree defs ``deserialize_and_load`` needs.  None when the
    backend can't serialize."""
    try:
        from jax.experimental import serialize_executable

        payload = serialize_executable.serialize(compiled)
        if not isinstance(payload, tuple):
            payload = (payload,)
        return payload
    except Exception:  # noqa: BLE001
        return None


def _hlo_proto_size(compiled) -> int | None:
    """Fallback size estimate when the AOT serializer is unavailable:
    the serialized HLO proto; None when neither is available."""
    try:
        memory = compiled.memory_analysis()
        proto = getattr(memory, "serialized_hlo_proto", None)
        if proto:
            return len(proto)
    except Exception:  # noqa: BLE001
        pass
    return None


# -- device-time attribution --------------------------------------------------

_JOB: contextvars.ContextVar = contextvars.ContextVar(
    "lo_costs_job", default=None
)


def current_job() -> str | None:
    return _JOB.get()


@contextlib.contextmanager
def job_scope(name: str):
    """Bind the calling thread's dispatches to job ``name`` — the
    executor wraps job bodies (and tune trials: worker-pool threads
    don't inherit context) so the epoch loop attributes correctly."""
    token = _JOB.set(name)
    try:
        yield
    finally:
        _JOB.reset(token)


def attribute(duration_s: float, *, cost: ProgramCost | None = None,
              key: str | None = None, model: str | None = None,
              bucket: int | None = None,
              job: str | None = None) -> bool:
    """The per-dispatch accounting hook.  ``cost`` (or ``key`` to look
    it up) supplies the program's flops/bytes; ``job`` defaults to the
    ambient :func:`job_scope`.  Disabled, this is one config check."""
    if not enabled():
        return False
    if cost is None and key is not None:
        cost = get_ledger().get(key)
    return devtime().attribute(
        duration_s,
        flops=cost.flops if cost is not None else None,
        nbytes=cost.bytes_accessed if cost is not None else None,
        job=job if job is not None else _JOB.get(),
        model=model,
        bucket=bucket,
    )


def job_summary(name: str) -> dict | None:
    """The job's accumulated device-time doc (None when nothing was
    attributed) — the executor stamps it into finished-job metadata."""
    if not enabled():
        return None
    return devtime().job_summary(name, peak_flops=peak_flops())


def serving_totals() -> dict:
    """Aggregate over served models (the tfevents serving_* scalars):
    device seconds, flops, and MFU when a peak is configured."""
    if not enabled():
        return {"deviceTimeS": 0.0, "flops": 0.0, "dispatches": 0}
    snap = devtime().snapshot(peak_flops=peak_flops())
    device_s = sum(
        m["deviceTimeS"] for m in snap["models"].values()
    )
    flops = sum(m["flops"] for m in snap["models"].values())
    out = {
        "deviceTimeS": round(device_s, 6),
        "flops": flops,
        "dispatches": sum(
            m["dispatches"] for m in snap["models"].values()
        ),
    }
    util = mfu(flops, device_s, peak_flops=peak_flops())
    if util is not None:
        out["mfu"] = util
    return out


def snapshot() -> dict:
    """Everything, JSON-shaped — the monitoring endpoint's view."""
    return {
        "enabled": enabled(),
        "peakFlopsPerChip": peak_flops(),
        "ledger": get_ledger().snapshot() if enabled() else {},
        "deviceTime": (
            devtime().snapshot(peak_flops=peak_flops())
            if enabled() else {}
        ),
    }
