"""Deployment CLI — the reference's ``run.sh``/container entrypoints
(reference: run.sh:32, docker-compose service commands) as one binary:

    python -m learningorchestra_tpu serve
        REST API server on LO_TPU_API_PORT (default 80).

    python -m learningorchestra_tpu coordinator --host 0.0.0.0 --port 7070
        Multi-host control plane (replaces Ray GCS + client,
        SURVEY §5.8).

    python -m learningorchestra_tpu agent --coordinator HOST:PORT \\
            [--id ID] [--capacity N]
        Per-host worker: registers, heartbeats, leases distributed
        tasks (replaces a Ray worker joining the head node).

    python -m learningorchestra_tpu standby --primary HOST:PORT \\
            --primary-store DIR --replica DIR --port N
        Warm standby: ships the primary's WALs, health-checks it, and
        on sustained failure promotes itself to the serving primary
        (replaces the mongo replica set's automatic election,
        reference: docker-compose.yml:42-90; see store/ha.py).
"""

from __future__ import annotations

import argparse
import signal
import socket
import sys
import time


def _cmd_serve(args) -> int:
    if getattr(args, "port", None):
        # Before the config tree is first built: from_env reads it.
        # An argv port also lets supervisors (deploy/run_local.sh)
        # identify the process for cleanup — env vars are invisible
        # to pgrep/pkill.
        import os

        os.environ["LO_TPU_API_PORT"] = str(args.port)
    from learningorchestra_tpu.api.server import serve

    serve()
    return 0


def _cmd_coordinator(args) -> int:
    from learningorchestra_tpu.parallel.coordinator import Coordinator

    coord = Coordinator(host=args.host, port=args.port).start()
    print(f"coordinator listening on {coord.address}", flush=True)
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    except AttributeError:
        # signal.pause is POSIX-only; fall back to a sleep loop.
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    coord.stop()
    return 0


def _cmd_agent(args) -> int:
    # Importing launch registers the named multihost task functions
    # (lo.multihost_fit, ...) before the agent starts leasing work.
    import learningorchestra_tpu.parallel.launch  # noqa: F401
    from learningorchestra_tpu.parallel.coordinator import HostAgent

    agent_id = args.id or f"{socket.gethostname()}-{int(time.time())}"
    agent = HostAgent(
        args.coordinator, agent_id, capacity=args.capacity
    )
    agent.serve()
    print(
        f"agent {agent_id} polling coordinator {args.coordinator}",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        agent.stop()
    return 0


def _cmd_standby(args) -> int:
    from learningorchestra_tpu.store.ha import run_standby

    run_standby(
        args.primary,
        args.primary_store,
        args.replica,
        args.port,
        check_interval=args.interval,
        max_misses=args.misses,
        host=args.host,
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="learningorchestra_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    serve_p = sub.add_parser("serve", help="run the REST API server")
    serve_p.add_argument(
        "--port", type=int, default=None,
        help="overrides LO_TPU_API_PORT",
    )

    coord = sub.add_parser("coordinator", help="run the control plane")
    coord.add_argument("--host", default="0.0.0.0")
    coord.add_argument("--port", type=int, default=7070)

    agent = sub.add_parser("agent", help="run a per-host worker agent")
    agent.add_argument("--coordinator", required=True,
                       help="coordinator HOST:PORT")
    agent.add_argument("--id", default=None)
    agent.add_argument("--capacity", type=int, default=1)

    standby = sub.add_parser(
        "standby", help="warm standby with automatic promotion"
    )
    standby.add_argument("--primary", required=True,
                         help="primary API HOST:PORT to health-check")
    standby.add_argument("--primary-store", default=None,
                         help="primary's store directory (WAL source) "
                              "when a mount is shared; omit to ship "
                              "WALs over the primary's /replication "
                              "HTTP routes (no shared storage)")
    standby.add_argument("--replica", required=True,
                         help="local replica directory")
    standby.add_argument("--port", type=int, required=True,
                         help="port to serve on after promotion")
    standby.add_argument("--host", default="0.0.0.0")
    standby.add_argument("--interval", type=float, default=0.5,
                         help="seconds between sync+health probes")
    standby.add_argument("--misses", type=int, default=4,
                         help="consecutive failed probes before takeover")

    args = parser.parse_args(argv)
    return {
        "serve": _cmd_serve,
        "coordinator": _cmd_coordinator,
        "agent": _cmd_agent,
        "standby": _cmd_standby,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
