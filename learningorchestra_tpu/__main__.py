"""Deployment CLI — the reference's ``run.sh``/container entrypoints
(reference: run.sh:32, docker-compose service commands) as one binary:

    python -m learningorchestra_tpu serve
        REST API server on LO_TPU_API_PORT (default 80).

    python -m learningorchestra_tpu coordinator --host 0.0.0.0 --port 7070
        Multi-host control plane (replaces Ray GCS + client,
        SURVEY §5.8).

    python -m learningorchestra_tpu agent --coordinator HOST:PORT \\
            [--id ID] [--capacity N]
        Per-host worker: registers, heartbeats, leases distributed
        tasks (replaces a Ray worker joining the head node).
"""

from __future__ import annotations

import argparse
import signal
import socket
import sys
import time


def _cmd_serve(_args) -> int:
    from learningorchestra_tpu.api.server import serve

    serve()
    return 0


def _cmd_coordinator(args) -> int:
    from learningorchestra_tpu.parallel.coordinator import Coordinator

    coord = Coordinator(host=args.host, port=args.port).start()
    print(f"coordinator listening on {coord.address}", flush=True)
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    except AttributeError:
        # signal.pause is POSIX-only; fall back to a sleep loop.
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    coord.stop()
    return 0


def _cmd_agent(args) -> int:
    # Importing launch registers the named multihost task functions
    # (lo.multihost_fit, ...) before the agent starts leasing work.
    import learningorchestra_tpu.parallel.launch  # noqa: F401
    from learningorchestra_tpu.parallel.coordinator import HostAgent

    agent_id = args.id or f"{socket.gethostname()}-{int(time.time())}"
    agent = HostAgent(
        args.coordinator, agent_id, capacity=args.capacity
    )
    agent.serve()
    print(
        f"agent {agent_id} polling coordinator {args.coordinator}",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        agent.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="learningorchestra_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("serve", help="run the REST API server")

    coord = sub.add_parser("coordinator", help="run the control plane")
    coord.add_argument("--host", default="0.0.0.0")
    coord.add_argument("--port", type=int, default=7070)

    agent = sub.add_parser("agent", help="run a per-host worker agent")
    agent.add_argument("--coordinator", required=True,
                       help="coordinator HOST:PORT")
    agent.add_argument("--id", default=None)
    agent.add_argument("--capacity", type=int, default=1)

    args = parser.parse_args(argv)
    return {
        "serve": _cmd_serve,
        "coordinator": _cmd_coordinator,
        "agent": _cmd_agent,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
