"""ctypes binding for the native (C++) runtime — see ``native/src``.

``liblodstore.so`` is the native document-store + CSV-ingest engine: the
system-of-record role MongoDB (a C++ server) plays in the reference
deployment (reference: docker-compose.yml:42-90), built first-party.  The
WAL format is byte-compatible with the pure-Python ``DocumentStore``, so
either backend can open the other's data directory.

``ensure_built()`` compiles the library on demand (g++, see
``native/Makefile``); when no toolchain is available everything falls
back to the Python backend — the native layer is an accelerator, not a
dependency.
"""

from __future__ import annotations

import ctypes
import json
import subprocess
from pathlib import Path
from typing import Any, Iterable

from learningorchestra_tpu import faults
from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.store.document_store import (
    DuplicateKey,
    NoSuchCollection,
    _match,
)

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_NATIVE_DIR = _REPO_ROOT / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "liblodstore.so"

_build_lock = make_lock("native._build_lock")
_lib: ctypes.CDLL | None = None
_build_failed = False


def ensure_built() -> Path | None:
    """Build (if stale/missing) and return the shared library path."""
    global _build_failed
    with _build_lock:
        if _build_failed:
            return None
        src = _NATIVE_DIR / "src" / "docstore.cpp"
        if not src.exists():
            _build_failed = True
            return None
        if (
            not _LIB_PATH.exists()
            or _LIB_PATH.stat().st_mtime < src.stat().st_mtime
        ):
            try:
                subprocess.run(
                    ["make", "-C", str(_NATIVE_DIR)],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception:
                _build_failed = True
                return None
        return _LIB_PATH if _LIB_PATH.exists() else None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_char_p = ctypes.c_char_p
    i64 = ctypes.c_int64
    ll = ctypes.c_longlong
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    p_ll = ctypes.POINTER(ctypes.c_longlong)
    # Returned buffers are malloc'd char*; keep them as void* so ctypes
    # doesn't copy-and-lose the pointer we must pass to lods_free.
    buf_t = ctypes.c_void_p

    lib.lods_last_error.restype = c_char_p
    lib.lods_free.argtypes = [buf_t]
    lib.lods_open.argtypes = [c_char_p, ctypes.c_int]
    lib.lods_open.restype = i64
    lib.lods_close.argtypes = [i64]
    lib.lods_has_collection.argtypes = [i64, c_char_p]
    lib.lods_list_collections.argtypes = [i64, p_i64]
    lib.lods_list_collections.restype = buf_t
    lib.lods_insert_many.argtypes = [i64, c_char_p, c_char_p, i64, p_ll]
    lib.lods_insert_many.restype = i64
    lib.lods_insert_at.argtypes = [i64, c_char_p, c_char_p, ll, ctypes.c_int]
    lib.lods_update.argtypes = [i64, c_char_p, ll, c_char_p]
    lib.lods_delete.argtypes = [i64, c_char_p, ll]
    lib.lods_find_one.argtypes = [i64, c_char_p, ll, p_i64]
    lib.lods_find_one.restype = buf_t
    lib.lods_scan.argtypes = [i64, c_char_p, i64, i64, p_i64]
    lib.lods_scan.restype = buf_t
    lib.lods_count.argtypes = [i64, c_char_p]
    lib.lods_count.restype = i64
    lib.lods_next_id.argtypes = [i64, c_char_p]
    lib.lods_next_id.restype = ll
    lib.lods_value_counts.argtypes = [i64, c_char_p, c_char_p, p_i64]
    lib.lods_value_counts.restype = buf_t
    lib.lods_drop.argtypes = [i64, c_char_p]
    lib.lods_compact.argtypes = [i64, c_char_p]
    lib.lods_csv_parse.argtypes = [c_char_p, i64, ctypes.c_int, p_i64]
    lib.lods_csv_parse.restype = buf_t
    lib.lods_csv_numeric_chunk.argtypes = [
        c_char_p, i64, ctypes.c_int, i64,
        ctypes.POINTER(ctypes.c_double), i64, p_i64, p_i64, p_i64,
    ]
    lib.lods_csv_numeric_chunk.restype = i64
    lib.lods_project.argtypes = [i64, c_char_p, c_char_p, c_char_p]
    lib.lods_project.restype = i64
    return lib


def load_library() -> ctypes.CDLL | None:
    global _lib
    if _lib is not None:
        return _lib
    path = ensure_built()
    if path is None:
        return None
    with _build_lock:
        if _lib is None:
            _lib = _bind(ctypes.CDLL(str(path)))
    return _lib


def native_available() -> bool:
    return load_library() is not None


def _raise_native(lib: ctypes.CDLL):
    msg = lib.lods_last_error().decode()
    if "invalid collection name" in msg:
        raise ValueError(msg)  # match DocumentStore._validate_name
    raise RuntimeError(msg)


def _take(lib: ctypes.CDLL, ptr: int, length: int) -> bytes:
    """Copy a returned buffer and free the native allocation."""
    if not ptr:
        return b""
    try:
        return ctypes.string_at(ptr, length)
    finally:
        lib.lods_free(ptr)


def _dumps(doc: dict) -> bytes:
    d = {k: v for k, v in doc.items() if k != "_id"}
    return json.dumps(d, default=str).encode()


def csv_numeric_chunk(data: bytes, ncols: int, *, is_final: bool,
                      bad_counts, float_counts=None,
                      max_rows: int | None = None):
    """Numeric CSV records → ((rows, ncols) float64 array, consumed).

    Only complete newline-terminated records are consumed unless
    ``is_final``; feed ``data[consumed:]`` + the next read back in.
    ``bad_counts`` is a caller-owned int64 array of length ``ncols``
    accumulating non-empty-unparseable cell counts across chunks (the
    "column is not numeric" contract check happens at close).
    ``float_counts`` (same shape, optional) accumulates FLOAT-FORMATTED
    cell counts — "5.0"/"1e3"/int64-overflow — so the sharded writer
    can type columns by text format exactly like the Python row path's
    ``_infer`` (a column is int only if every cell is int-formatted)."""
    import numpy as np

    lib = load_library()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if max_rows is None:
        # A minimal record is ncols-1 commas + a newline = ncols bytes
        # (all-empty cells), so bytes/ncols bounds the row count —
        # far below a byte-per-row worst-case buffer.
        max_rows = len(data) // max(1, ncols) + 2
    out = np.empty((max_rows, ncols), np.float64)
    consumed = ctypes.c_int64()
    rows = lib.lods_csv_numeric_chunk(
        data, len(data), 1 if is_final else 0, ncols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        max_rows,
        bad_counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        (float_counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
         if float_counts is not None else None),
        ctypes.byref(consumed),
    )
    if rows < 0:
        _raise_native(lib)
    if rows < max_rows:
        # A view would pin the whole worst-case allocation (~8x the
        # chunk bytes) in the caller's block queue until shard flush.
        return out[:rows].copy(), consumed.value
    return out, consumed.value


def csv_parse(data: bytes, infer_types: bool = True):
    """CSV bytes → (fields, jsonl doc lines) via the native parser."""
    lib = load_library()
    if lib is None:
        raise RuntimeError("native library unavailable")
    out_len = ctypes.c_int64()
    ptr = lib.lods_csv_parse(
        data, len(data), 1 if infer_types else 0, ctypes.byref(out_len)
    )
    if not ptr:
        raise ValueError(lib.lods_last_error().decode())
    payload = _take(lib, ptr, out_len.value)
    head, _, rest = payload.partition(b"\n")
    return json.loads(head), rest


class NativeDocumentStore:
    """Drop-in replacement for ``DocumentStore`` backed by liblodstore.

    Documents live in native memory as raw JSON; Python materialises them
    only on read.  Query filtering beyond id-ordered paging reuses the
    Python ``_match`` operator set over a native scan.
    """

    def __init__(self, root: str | Path, durable_writes: bool = False):
        self._lib = load_library()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self.root = Path(root).expanduser()
        self.root.mkdir(parents=True, exist_ok=True)
        self._h = self._lib.lods_open(
            str(self.root).encode(), 1 if durable_writes else 0
        )
        if self._h < 0:
            _raise_native(self._lib)
        self._closed = False

    # -- collection lifecycle ----------------------------------------------

    def collection_exists(self, name: str) -> bool:
        return self._lib.lods_has_collection(self._h, name.encode()) == 1

    def list_collections(self) -> list[str]:
        n = ctypes.c_int64()
        ptr = self._lib.lods_list_collections(self._h, ctypes.byref(n))
        data = _take(self._lib, ptr, n.value)
        return [ln for ln in data.decode().splitlines() if ln]

    def drop(self, name: str) -> bool:
        return self._lib.lods_drop(self._h, name.encode()) == 1

    # -- writes -------------------------------------------------------------
    # Every write entry point carries the same chaos probe as the
    # Python backend's WAL append (document_store.py _append): an
    # armed ``store.wal_write`` schedule must fire no matter which
    # backend the deployment resolved — a probe that exists on only
    # one backend would fake a green drill on the other.

    def insert_one(self, name: str, doc: dict, _id: int | None = None) -> int:
        faults.hit("store.wal_write")
        if _id is None:
            first = ctypes.c_longlong()
            payload = _dumps(doc) + b"\n"
            n = self._lib.lods_insert_many(
                self._h, name.encode(), payload, len(payload),
                ctypes.byref(first),
            )
            if n < 0:
                _raise_native(self._lib)
            return int(first.value)
        rc = self._lib.lods_insert_at(
            self._h, name.encode(), _dumps(doc), _id, 0
        )
        if rc < 0:
            _raise_native(self._lib)
        return _id

    def insert_unique(self, name: str, doc: dict, _id: int) -> int:
        faults.hit("store.wal_write")
        rc = self._lib.lods_insert_at(
            self._h, name.encode(), _dumps(doc), _id, 1
        )
        if rc == -2:
            raise DuplicateKey(f"{name}[{_id}]")
        if rc < 0:
            _raise_native(self._lib)
        return _id

    def insert_many(self, name: str, docs: Iterable[dict]) -> int:
        payload = b"\n".join(_dumps(d) for d in docs)
        if not payload:
            return 0
        return self.insert_jsonl(name, payload + b"\n")

    def insert_jsonl(self, name: str, jsonl: bytes) -> int:
        """Fast path: pre-serialized JSONL docs (no ``_id`` fields) go
        straight into the native engine — paired with ``csv_parse`` this
        makes CSV ingest bypass Python object materialisation entirely
        (the reference's per-row hot loop, database_api_image/
        database.py:139-151)."""
        faults.hit("store.wal_write")
        first = ctypes.c_longlong()
        n = self._lib.lods_insert_many(
            self._h, name.encode(), jsonl, len(jsonl), ctypes.byref(first)
        )
        if n < 0:
            _raise_native(self._lib)
        return int(n)

    def update_one(self, name: str, _id: int, fields: dict) -> bool:
        faults.hit("store.wal_write")
        rc = self._lib.lods_update(
            self._h, name.encode(), _id, _dumps(fields)
        )
        if rc < 0:
            raise NoSuchCollection(name)
        return rc == 1

    def delete_one(self, name: str, _id: int) -> bool:
        faults.hit("store.wal_write")
        rc = self._lib.lods_delete(self._h, name.encode(), _id)
        if rc < 0:
            raise NoSuchCollection(name)
        return rc == 1

    # -- reads --------------------------------------------------------------

    def _scan(self, name: str, skip: int = 0, limit: int = -1) -> list[dict]:
        n = ctypes.c_int64()
        ptr = self._lib.lods_scan(
            self._h, name.encode(), skip, limit, ctypes.byref(n)
        )
        if not ptr and not self.collection_exists(name):
            raise NoSuchCollection(name)
        data = _take(self._lib, ptr, n.value)
        return [json.loads(ln) for ln in data.splitlines() if ln]

    def find(
        self,
        name: str,
        query: dict | None = None,
        sort_key: str = "_id",
        skip: int = 0,
        limit: int | None = None,
    ) -> list[dict]:
        if not query and sort_key == "_id":
            return self._scan(name, skip, -1 if limit is None else limit)
        docs = [d for d in self._scan(name) if _match(d, query)]
        if sort_key != "_id":
            docs.sort(
                key=lambda d: (d.get(sort_key) is None, d.get(sort_key))
            )
        if skip:
            docs = docs[skip:]
        if limit is not None:
            docs = docs[:limit]
        return docs

    def find_one(self, name: str, _id: int) -> dict | None:
        n = ctypes.c_int64()
        ptr = self._lib.lods_find_one(
            self._h, name.encode(), _id, ctypes.byref(n)
        )
        if not ptr:
            return None
        return json.loads(_take(self._lib, ptr, n.value))

    def count(self, name: str, query: dict | None = None) -> int:
        if query is None:
            n = self._lib.lods_count(self._h, name.encode())
            if n < 0:
                raise NoSuchCollection(name)
            return int(n)
        return sum(1 for d in self._scan(name) if _match(d, query))

    def aggregate_counts(
        self, name: str, field: str, exclude_ids: tuple = (0,)
    ) -> dict[Any, int]:
        if tuple(exclude_ids) != (0,):
            counts: dict[Any, int] = {}
            for doc in self._scan(name):
                if doc.get("_id") in exclude_ids \
                        or doc.get("docType") == "execution":
                    continue
                val = doc.get(field)
                if isinstance(val, (list, dict)):
                    val = json.dumps(val, default=str)
                counts[val] = counts.get(val, 0) + 1
            return counts
        n = ctypes.c_int64()
        ptr = self._lib.lods_value_counts(
            self._h, name.encode(), field.encode(), ctypes.byref(n)
        )
        if not ptr and not self.collection_exists(name):
            raise NoSuchCollection(name)
        data = _take(self._lib, ptr, n.value)
        counts = {}
        for ln in data.splitlines():
            if not ln:
                continue
            rec = json.loads(ln)
            key = rec["k"]
            if isinstance(key, (list, dict)):
                key = json.dumps(key, default=str)
            counts[key] = counts.get(key, 0) + rec["n"]
        return counts

    def project(self, src: str, dst: str, fields: list[str]) -> int:
        """Native column projection src → dst (data rows only); returns
        rows written.  The Spark-projection replacement (SURVEY §2.3)."""
        n = self._lib.lods_project(
            self._h, src.encode(), dst.encode(),
            "\n".join(fields).encode(),
        )
        if n < 0:
            _raise_native(self._lib)
        return int(n)

    # -- maintenance --------------------------------------------------------

    def compact(self, name: str) -> None:
        if self._lib.lods_compact(self._h, name.encode()) < 0:
            raise NoSuchCollection(name)

    def close(self) -> None:
        if not self._closed:
            self._lib.lods_close(self._h)
            self._closed = True
