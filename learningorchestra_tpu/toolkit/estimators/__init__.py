"""JAX-native classical estimators.

Stand-ins for the sklearn / Spark-MLlib estimator surface the reference
orchestrates (reference: microservices/builder_image/utils.py:119-123 —
LR/DT/RF/GB/NB whitelist — and the arbitrary ``sklearn.*`` instantiation of
model_image/model.py:92-162).  Each is a ground-up jax.numpy implementation:
dense vectorized math that XLA tiles onto the MXU, not a wrapper over
sklearn's C extensions.
"""
