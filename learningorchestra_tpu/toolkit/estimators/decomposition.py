"""PCA and t-SNE — the Explore-service projections.

The reference's Explore path runs arbitrary sklearn classes and renders
scatterplots (reference: microservices/database_executor_image/
database_execution.py:92-188, utils.py:295-320); t-SNE is named in the
IMDb demo config (BASELINE.md config 3).  PCA is an SVD on the MXU; t-SNE
is the exact O(n²) algorithm as a jitted `lax.scan` — the pairwise-affinity
matrix is a dense matmul, which on TPU beats Barnes-Hut-style pointer
chasing for the few-thousand-point datasets Explore plots.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.toolkit.base import Estimator, as_array
from learningorchestra_tpu.toolkit.registry import register

_MODULE = "learningorchestra_tpu.toolkit.estimators.decomposition"


@register(_MODULE)
class PCA(Estimator):
    def __init__(self, n_components: int = 2):
        self.n_components = n_components
        self.mean_ = None
        self.components_ = None
        self.explained_variance_ratio_ = None

    def fit(self, x, y=None):
        x = as_array(x, jnp.float32)
        self.mean_ = jnp.mean(x, 0)
        xc = x - self.mean_
        _, s, vt = jnp.linalg.svd(xc, full_matrices=False)
        self.components_ = vt[: self.n_components]
        var = (s**2) / (x.shape[0] - 1)
        self.explained_variance_ratio_ = var[: self.n_components] / jnp.sum(
            var
        )
        return self

    def transform(self, x):
        x = as_array(x, jnp.float32)
        return (x - self.mean_) @ self.components_.T

    def fit_transform(self, x, y=None):
        return self.fit(x).transform(x)

    def inverse_transform(self, z):
        return as_array(z, jnp.float32) @ self.components_ + self.mean_


def _pairwise_sq_dists(x):
    s = jnp.sum(x * x, axis=1)
    return s[:, None] - 2.0 * x @ x.T + s[None, :]


@functools.partial(jax.jit, static_argnames=("max_bisect",))
def _binary_search_perplexity(d2, target_entropy, max_bisect: int = 50):
    """Per-point beta (precision) search so each row's conditional
    distribution hits the target perplexity."""
    n = d2.shape[0]
    inf = jnp.float32(jnp.inf)

    def row_probs(beta):
        p = jnp.exp(-d2 * beta[:, None])
        p = p * (1.0 - jnp.eye(n))
        psum = jnp.maximum(jnp.sum(p, axis=1, keepdims=True), 1e-12)
        return p / psum

    def entropy(beta):
        p = row_probs(beta)
        return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0), axis=1)

    def body(_, state):
        beta, lo, hi = state
        h = entropy(beta)
        too_high = h > target_entropy  # entropy too high → beta too small
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(
            too_high,
            jnp.where(jnp.isinf(hi), beta * 2.0, (beta + hi) / 2.0),
            jnp.where(lo == 0, beta / 2.0, (beta + lo) / 2.0),
        )
        return beta, lo, hi

    beta0 = jnp.ones((n,), jnp.float32)
    lo0 = jnp.zeros((n,), jnp.float32)
    hi0 = jnp.full((n,), inf)
    beta, _, _ = jax.lax.fori_loop(0, max_bisect, body, (beta0, lo0, hi0))
    return row_probs(beta)


@functools.partial(
    jax.jit, static_argnames=("n_iter", "early_exaggeration_iters")
)
def _tsne_optimize(
    p, y0, learning_rate, n_iter: int, early_exaggeration_iters: int
):
    n = p.shape[0]
    eye = jnp.eye(n)

    def grad_kl(y, p_eff):
        d2 = _pairwise_sq_dists(y)
        num = 1.0 / (1.0 + d2)
        num = num * (1.0 - eye)
        q = num / jnp.maximum(jnp.sum(num), 1e-12)
        pq = (p_eff - q) * num  # (n, n)
        return 4.0 * (
            y * jnp.sum(pq, axis=1, keepdims=True) - pq @ y
        )

    def step(carry, i):
        y, vel = carry
        exag = jnp.where(i < early_exaggeration_iters, 12.0, 1.0)
        g = grad_kl(y, p * exag)
        momentum = jnp.where(i < early_exaggeration_iters, 0.5, 0.8)
        vel = momentum * vel - learning_rate * g
        y = y + vel
        return (y, vel), None

    (y, _), _ = jax.lax.scan(
        step, (y0, jnp.zeros_like(y0)), jnp.arange(n_iter)
    )
    return y


@register(_MODULE)
class TSNE(Estimator):
    """Exact t-SNE, fully jitted (dense affinities → MXU-friendly)."""

    def __init__(
        self,
        n_components: int = 2,
        perplexity: float = 30.0,
        learning_rate: float = 200.0,
        n_iter: int = 500,
        random_state: int = 0,
    ):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.random_state = random_state
        self.embedding_ = None

    def fit_transform(self, x, y=None):
        x = as_array(x, jnp.float32)
        n = x.shape[0]
        d2 = _pairwise_sq_dists(x)
        cond = _binary_search_perplexity(
            d2, jnp.log(jnp.float32(self.perplexity))
        )
        p = (cond + cond.T) / (2.0 * n)
        p = jnp.maximum(p, 1e-12)
        rng = np.random.default_rng(self.random_state)
        y0 = jnp.asarray(
            rng.normal(scale=1e-4, size=(n, self.n_components)),
            jnp.float32,
        )
        emb = _tsne_optimize(
            p,
            y0,
            self.learning_rate,
            n_iter=self.n_iter,
            early_exaggeration_iters=min(250, self.n_iter // 2),
        )
        self.embedding_ = emb
        return emb

    def fit(self, x, y=None):
        self.fit_transform(x)
        return self
