"""Linear models: LinearRegression, Ridge, LogisticRegression.

JAX-native replacements for the reference's ``sklearn.linear_model``
surface (instantiable via the model service, reference:
microservices/model_image/model.py:92-162) and Spark MLlib's
LogisticRegression (builder whitelist, builder_image/utils.py:119-123).

Design: closed-form solves where they exist (lstsq / cholesky on the MXU);
logistic regression is a full-batch jitted optimizer loop (`lax.scan` over
optax-adam steps — static trip count, no host round-trips per step).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learningorchestra_tpu.toolkit.base import (
    Estimator,
    as_array,
    encode_classes,
)
from learningorchestra_tpu.toolkit.registry import register

_MODULE = "learningorchestra_tpu.toolkit.estimators.linear"


def _add_bias(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)


@register(_MODULE)
class LinearRegression(Estimator):
    def __init__(self, fit_intercept: bool = True):
        self.fit_intercept = fit_intercept
        self.coef_ = None
        self.intercept_ = None

    def fit(self, x, y):
        x = as_array(x, jnp.float32)
        y = as_array(y, jnp.float32)
        squeeze = y.ndim == 1
        y2 = y.reshape(y.shape[0], -1)
        xb = _add_bias(x) if self.fit_intercept else x
        w, *_ = jnp.linalg.lstsq(xb, y2)
        if self.fit_intercept:
            self.coef_, self.intercept_ = w[:-1], w[-1]
        else:
            self.coef_ = w
            self.intercept_ = jnp.zeros(y2.shape[1], y2.dtype)
        if squeeze:
            self.coef_ = self.coef_[:, 0]
            self.intercept_ = self.intercept_[0]
        return self

    def predict(self, x):
        x = as_array(x, jnp.float32)
        coef = self.coef_ if self.coef_.ndim == 2 else self.coef_[:, None]
        out = x @ coef + self.intercept_
        return out[:, 0] if self.coef_.ndim == 1 else out

    def score(self, x, y):  # R^2 for regressors
        y = np.asarray(as_array(y, jnp.float32))
        pred = np.asarray(self.predict(x)).reshape(y.shape)
        ss_res = float(((y - pred) ** 2).sum())
        ss_tot = float(((y - y.mean(0)) ** 2).sum())
        return 1.0 - ss_res / max(ss_tot, 1e-12)


@register(_MODULE)
class Ridge(LinearRegression):
    def __init__(self, alpha: float = 1.0, fit_intercept: bool = True):
        super().__init__(fit_intercept=fit_intercept)
        self.alpha = alpha

    def fit(self, x, y):
        x = as_array(x, jnp.float32)
        y = as_array(y, jnp.float32)
        squeeze = y.ndim == 1
        y2 = y.reshape(y.shape[0], -1)
        xb = _add_bias(x) if self.fit_intercept else x
        d = xb.shape[1]
        reg = self.alpha * jnp.eye(d, dtype=xb.dtype)
        if self.fit_intercept:
            reg = reg.at[-1, -1].set(0.0)  # don't penalize the bias
        w = jnp.linalg.solve(xb.T @ xb + reg, xb.T @ y2)
        if self.fit_intercept:
            self.coef_, self.intercept_ = w[:-1], w[-1]
        else:
            self.coef_ = w
            self.intercept_ = jnp.zeros(y2.shape[1], y2.dtype)
        if squeeze:
            self.coef_ = self.coef_[:, 0]
            self.intercept_ = self.intercept_[0]
        return self


@functools.partial(jax.jit, static_argnames=("n_steps",))
def _fit_logreg(x, y_onehot, w0, b0, lr, l2, n_steps: int):
    """Full-batch softmax regression via lax.scan over adam updates."""
    opt = optax.adam(lr)

    def loss_fn(params):
        w, b = params
        logits = x @ w + b
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.mean(jnp.sum(y_onehot * logp, axis=-1))
        return nll + l2 * jnp.sum(w * w)

    def step(carry, _):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    init = ((w0, b0), opt.init((w0, b0)))
    (params, _), losses = jax.lax.scan(step, init, None, length=n_steps)
    return params, losses


@register(_MODULE)
class LogisticRegression(Estimator):
    """Multinomial logistic regression, full-batch adam, jit-compiled."""

    def __init__(
        self,
        max_iter: int = 200,
        learning_rate: float = 0.1,
        C: float = 1.0,
        fit_intercept: bool = True,
    ):
        self.max_iter = max_iter
        self.learning_rate = learning_rate
        self.C = C
        self.fit_intercept = fit_intercept
        self.classes_ = None
        self.coef_ = None
        self.intercept_ = None
        self.losses_ = None

    def fit(self, x, y):
        x = as_array(x, jnp.float32)
        self.classes_, y_idx = encode_classes(y)
        k = len(self.classes_)
        y1h = jax.nn.one_hot(jnp.asarray(y_idx), k)
        w0 = jnp.zeros((x.shape[1], k), jnp.float32)
        b0 = jnp.zeros((k,), jnp.float32)
        l2 = 1.0 / (2.0 * self.C * x.shape[0])
        (w, b), losses = _fit_logreg(
            x, y1h, w0, b0, self.learning_rate, l2, n_steps=self.max_iter
        )
        self.coef_, self.intercept_ = w, b
        self.losses_ = np.asarray(losses)
        return self

    def decision_function(self, x):
        x = as_array(x, jnp.float32)
        return x @ self.coef_ + self.intercept_

    def predict_proba(self, x):
        return jax.nn.softmax(self.decision_function(x), axis=-1)

    def predict(self, x):
        idx = np.asarray(jnp.argmax(self.decision_function(x), axis=-1))
        return self.classes_[idx]


@register(_MODULE)
class SGDClassifier(LogisticRegression):
    """Alias surface for sklearn.linear_model.SGDClassifier (log loss)."""

    def __init__(self, max_iter: int = 200, learning_rate: float = 0.05,
                 C: float = 1.0):
        super().__init__(
            max_iter=max_iter, learning_rate=learning_rate, C=C
        )
