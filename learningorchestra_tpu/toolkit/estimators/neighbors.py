"""KNeighborsClassifier — brute-force distances as one (n, m) matmul.

On TPU the "smart" tree-based kNN of sklearn loses to a single dense
distance computation that XLA tiles onto the MXU; this implementation is
brute-force by design.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.toolkit.base import (
    Estimator,
    as_array,
    encode_classes,
)
from learningorchestra_tpu.toolkit.registry import register

_MODULE = "learningorchestra_tpu.toolkit.estimators.neighbors"


@functools.partial(jax.jit, static_argnames=("k", "n_classes"))
def _knn_votes(train_x, train_y, test_x, k: int, n_classes: int):
    d = (
        jnp.sum(test_x * test_x, 1, keepdims=True)
        - 2.0 * test_x @ train_x.T
        + jnp.sum(train_x * train_x, 1)[None]
    )
    _, idx = jax.lax.top_k(-d, k)  # (m, k) nearest indices
    votes = jax.nn.one_hot(train_y[idx], n_classes).sum(axis=1)
    return votes


@register(_MODULE)
class KNeighborsClassifier(Estimator):
    def __init__(self, n_neighbors: int = 5):
        self.n_neighbors = n_neighbors
        self.classes_ = None
        self._x = None
        self._y = None

    def fit(self, x, y):
        self._x = as_array(x, jnp.float32)
        self.classes_, y_idx = encode_classes(y)
        self._y = jnp.asarray(y_idx)
        return self

    def predict_proba(self, x):
        votes = _knn_votes(
            self._x,
            self._y,
            as_array(x, jnp.float32),
            k=self.n_neighbors,
            n_classes=len(self.classes_),
        )
        return votes / jnp.sum(votes, axis=1, keepdims=True)

    def predict(self, x):
        votes = _knn_votes(
            self._x,
            self._y,
            as_array(x, jnp.float32),
            k=self.n_neighbors,
            n_classes=len(self.classes_),
        )
        return self.classes_[np.asarray(jnp.argmax(votes, axis=1))]
