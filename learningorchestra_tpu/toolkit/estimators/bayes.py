"""Naive Bayes (Gaussian + Multinomial) on jax.numpy.

Covers the reference's NB surface: Spark MLlib NaiveBayes in the builder
whitelist (reference: microservices/builder_image/utils.py:119-123) and
``sklearn.naive_bayes`` via the model service.  Fitting is a handful of
segment-sums — fully vectorized, one XLA launch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.toolkit.base import (
    Estimator,
    as_array,
    encode_classes,
)
from learningorchestra_tpu.toolkit.registry import register

_MODULE = "learningorchestra_tpu.toolkit.estimators.bayes"


@register(_MODULE)
class GaussianNB(Estimator):
    def __init__(self, var_smoothing: float = 1e-9):
        self.var_smoothing = var_smoothing
        self.classes_ = None
        self.theta_ = None  # (k, d) means
        self.var_ = None  # (k, d) variances
        self.class_log_prior_ = None

    def fit(self, x, y):
        x = as_array(x, jnp.float32)
        self.classes_, y_idx = encode_classes(y)
        k = len(self.classes_)
        y1h = jax.nn.one_hot(jnp.asarray(y_idx), k, dtype=x.dtype)  # (n, k)
        counts = y1h.sum(0)  # (k,)
        sums = y1h.T @ x  # (k, d)
        self.theta_ = sums / counts[:, None]
        sq = y1h.T @ (x * x)
        var = sq / counts[:, None] - self.theta_**2
        eps = self.var_smoothing * jnp.max(jnp.var(x, axis=0))
        self.var_ = var + eps
        self.class_log_prior_ = jnp.log(counts / counts.sum())
        return self

    def _joint_log_likelihood(self, x):
        x = as_array(x, jnp.float32)
        # (n, k, d) broadcast collapsed to two matmul-shaped reductions.
        diff = x[:, None, :] - self.theta_[None, :, :]
        ll = -0.5 * jnp.sum(
            jnp.log(2.0 * jnp.pi * self.var_)[None] + diff**2 / self.var_[None],
            axis=-1,
        )
        return ll + self.class_log_prior_[None]

    def predict_proba(self, x):
        return jax.nn.softmax(self._joint_log_likelihood(x), axis=-1)

    def predict(self, x):
        idx = np.asarray(jnp.argmax(self._joint_log_likelihood(x), axis=-1))
        return self.classes_[idx]


@register(_MODULE)
class MultinomialNB(Estimator):
    def __init__(self, alpha: float = 1.0):
        self.alpha = alpha
        self.classes_ = None
        self.feature_log_prob_ = None
        self.class_log_prior_ = None

    def fit(self, x, y):
        x = as_array(x, jnp.float32)
        self.classes_, y_idx = encode_classes(y)
        k = len(self.classes_)
        y1h = jax.nn.one_hot(jnp.asarray(y_idx), k, dtype=x.dtype)
        counts = y1h.sum(0)
        feat = y1h.T @ x + self.alpha  # (k, d)
        self.feature_log_prob_ = jnp.log(feat) - jnp.log(
            feat.sum(1, keepdims=True)
        )
        self.class_log_prior_ = jnp.log(counts / counts.sum())
        return self

    def _joint_log_likelihood(self, x):
        x = as_array(x, jnp.float32)
        return x @ self.feature_log_prob_.T + self.class_log_prior_[None]

    def predict_proba(self, x):
        return jax.nn.softmax(self._joint_log_likelihood(x), axis=-1)

    def predict(self, x):
        idx = np.asarray(jnp.argmax(self._joint_log_likelihood(x), axis=-1))
        return self.classes_[idx]
