"""KMeans: kmeans++ seeding (host) + jitted Lloyd iterations (lax.scan).

Replaces ``sklearn.cluster.KMeans`` instantiable through the model service
(reference: microservices/model_image/model.py:92-162).  The assignment
step is one big (n, k) distance matmul — exactly the shape the MXU wants.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.toolkit.base import Estimator, as_array
from learningorchestra_tpu.toolkit.registry import register

_MODULE = "learningorchestra_tpu.toolkit.estimators.cluster"


@functools.partial(jax.jit, static_argnames=("n_iter",))
def _lloyd(x, centers0, n_iter: int):
    def assign(centers):
        # ||x-c||^2 = ||x||^2 - 2 x.c + ||c||^2 ; argmin over k.
        d = (
            jnp.sum(x * x, 1, keepdims=True)
            - 2.0 * x @ centers.T
            + jnp.sum(centers * centers, 1)[None]
        )
        return jnp.argmin(d, axis=1)

    def step(centers, _):
        labels = assign(centers)
        one_hot = jax.nn.one_hot(labels, centers.shape[0], dtype=x.dtype)
        counts = one_hot.sum(0)
        sums = one_hot.T @ x
        new = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1), centers
        )
        return new, None

    centers, _ = jax.lax.scan(step, centers0, None, length=n_iter)
    labels = assign(centers)
    dists = jnp.sum((x - centers[labels]) ** 2, axis=1)
    return centers, labels, jnp.sum(dists)


@register(_MODULE)
class KMeans(Estimator):
    def __init__(
        self,
        n_clusters: int = 8,
        max_iter: int = 100,
        random_state: int = 0,
    ):
        self.n_clusters = n_clusters
        self.max_iter = max_iter
        self.random_state = random_state
        self.cluster_centers_ = None
        self.labels_ = None
        self.inertia_ = None

    def _init_centers(self, x: np.ndarray) -> np.ndarray:
        """kmeans++ seeding on host (data-dependent control flow)."""
        rng = np.random.default_rng(self.random_state)
        n = x.shape[0]
        centers = [x[rng.integers(n)]]
        for _ in range(1, self.n_clusters):
            d2 = np.min(
                ((x[:, None, :] - np.stack(centers)[None]) ** 2).sum(-1),
                axis=1,
            )
            probs = d2 / max(d2.sum(), 1e-12)
            centers.append(x[rng.choice(n, p=probs)])
        return np.stack(centers)

    def fit(self, x, y=None):
        xj = as_array(x, jnp.float32)
        centers0 = jnp.asarray(
            self._init_centers(np.asarray(xj)), jnp.float32
        )
        centers, labels, inertia = _lloyd(xj, centers0, self.max_iter)
        self.cluster_centers_ = centers
        self.labels_ = np.asarray(labels)
        self.inertia_ = float(inertia)
        return self

    def predict(self, x):
        x = as_array(x, jnp.float32)
        c = self.cluster_centers_
        d = (
            jnp.sum(x * x, 1, keepdims=True)
            - 2.0 * x @ c.T
            + jnp.sum(c * c, 1)[None]
        )
        return np.asarray(jnp.argmin(d, axis=1))

    def score(self, x, y=None):
        x = as_array(x, jnp.float32)
        labels = jnp.asarray(self.predict(x))
        return -float(
            jnp.sum((x - self.cluster_centers_[labels]) ** 2)
        )
