"""Preprocessing transforms: StandardScaler, MinMaxScaler, OneHotEncoder.

The reference's Transform service instantiates exactly these kinds of
classes generically (``databaseExecutor`` with type=transform, reference:
microservices/database_executor_image/database_execution.py:92-188).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.toolkit.base import Estimator, as_array
from learningorchestra_tpu.toolkit.registry import register

_MODULE = "learningorchestra_tpu.toolkit.estimators.preprocessing"


@register(_MODULE)
class StandardScaler(Estimator):
    def __init__(self, with_mean: bool = True, with_std: bool = True):
        self.with_mean = with_mean
        self.with_std = with_std
        self.mean_ = None
        self.scale_ = None

    def fit(self, x, y=None):
        x = as_array(x, jnp.float32)
        self.mean_ = jnp.mean(x, 0) if self.with_mean else jnp.zeros(x.shape[1])
        std = jnp.std(x, 0) if self.with_std else jnp.ones(x.shape[1])
        self.scale_ = jnp.where(std == 0, 1.0, std)
        return self

    def transform(self, x):
        x = as_array(x, jnp.float32)
        return (x - self.mean_) / self.scale_

    def fit_transform(self, x, y=None):
        return self.fit(x).transform(x)

    def inverse_transform(self, x):
        return as_array(x, jnp.float32) * self.scale_ + self.mean_


@register(_MODULE)
class MinMaxScaler(Estimator):
    def __init__(self, feature_range: tuple = (0.0, 1.0)):
        self.feature_range = tuple(feature_range)
        self.min_ = None
        self.scale_ = None

    def fit(self, x, y=None):
        x = as_array(x, jnp.float32)
        lo, hi = jnp.min(x, 0), jnp.max(x, 0)
        span = jnp.where(hi - lo == 0, 1.0, hi - lo)
        a, b = self.feature_range
        self.scale_ = (b - a) / span
        self.min_ = a - lo * self.scale_
        return self

    def transform(self, x):
        return as_array(x, jnp.float32) * self.scale_ + self.min_

    def fit_transform(self, x, y=None):
        return self.fit(x).transform(x)


@register(_MODULE)
class OneHotEncoder(Estimator):
    def __init__(self):
        self.categories_ = None

    def fit(self, x, y=None):
        arr = np.asarray(x if not hasattr(x, "to_numpy") else x.to_numpy())
        if arr.ndim == 1:
            arr = arr[:, None]
        self.categories_ = [np.unique(arr[:, j]) for j in range(arr.shape[1])]
        return self

    def transform(self, x):
        arr = np.asarray(x if not hasattr(x, "to_numpy") else x.to_numpy())
        if arr.ndim == 1:
            arr = arr[:, None]
        cols = []
        for j, cats in enumerate(self.categories_):
            idx = np.searchsorted(cats, arr[:, j])
            idx = np.clip(idx, 0, len(cats) - 1)
            valid = cats[idx] == arr[:, j]
            block = np.zeros((arr.shape[0], len(cats)), np.float32)
            block[np.arange(arr.shape[0])[valid], idx[valid]] = 1.0
            cols.append(block)
        return jnp.asarray(np.concatenate(cols, axis=1))

    def fit_transform(self, x, y=None):
        return self.fit(x).transform(x)
