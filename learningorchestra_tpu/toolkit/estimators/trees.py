"""Decision trees, random forests, gradient boosting — histogram-based.

Covers the remaining Spark-MLlib builder whitelist (DecisionTree,
RandomForest, GBT — reference: microservices/builder_image/utils.py:119-123)
and ``sklearn.tree``/``sklearn.ensemble`` via the model service.

Design, TPU-first rather than a port of sklearn's Cython:
- features are quantized once into ≤256 bins (the XGBoost/LightGBM
  histogram trick), so split search is dense array math over
  (features × bins) — not per-sample comparisons;
- trees are built greedily on host (tree growth is inherently sequential
  pointer-y control flow — the wrong shape for XLA) but stored as flat
  arrays ``(feature, threshold, left, right, leaf_value)``;
- prediction is a jitted, fully-vectorized level-synchronous traversal:
  ``max_depth`` rounds of gather + select over the whole batch, no
  per-sample branching; forests vmap it over trees.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from learningorchestra_tpu.toolkit.base import (
    Estimator,
    as_array,
    encode_classes,
)
from learningorchestra_tpu.toolkit.registry import register

_MODULE = "learningorchestra_tpu.toolkit.estimators.trees"


# ---------------------------------------------------------------------------
# Quantization
# ---------------------------------------------------------------------------


def _quantize(x: np.ndarray, n_bins: int) -> tuple[np.ndarray, np.ndarray]:
    """Per-feature quantile binning.

    Returns (binned uint8/16 array (n, d), edges (d, n_bins-1) float32 with
    +inf padding).  bin b holds values in (edges[b-1], edges[b]].
    """
    n, d = x.shape
    edges = np.full((d, n_bins - 1), np.inf, np.float32)
    binned = np.zeros((n, d), np.int16)
    qs = np.linspace(0, 100, n_bins + 1)[1:-1]
    for j in range(d):
        col = x[:, j]
        e = np.unique(np.percentile(col, qs))
        edges[j, : len(e)] = e
        binned[:, j] = np.searchsorted(e, col, side="left")
    return binned, edges


# ---------------------------------------------------------------------------
# Flat tree + jitted prediction
# ---------------------------------------------------------------------------


class _FlatTree:
    """Arrays: feature(int32), threshold(f32), left/right(int32, -1=none),
    leaf_value (n_nodes, out_dim)."""

    __slots__ = ("feature", "threshold", "left", "right", "leaf_value",
                 "max_depth")

    def __init__(self, feature, threshold, left, right, leaf_value,
                 max_depth):
        self.feature = jnp.asarray(feature, jnp.int32)
        self.threshold = jnp.asarray(threshold, jnp.float32)
        self.left = jnp.asarray(left, jnp.int32)
        self.right = jnp.asarray(right, jnp.int32)
        self.leaf_value = jnp.asarray(leaf_value, jnp.float32)
        self.max_depth = int(max_depth)

    def stacked(self):
        return (self.feature, self.threshold, self.left, self.right,
                self.leaf_value)


@functools.partial(jax.jit, static_argnames=("depth",))
def _traverse(feature, threshold, left, right, leaf_value, x, depth: int):
    """Level-synchronous tree walk for a whole batch at once."""
    n = x.shape[0]
    node = jnp.zeros((n,), jnp.int32)

    def body(_, node):
        f = feature[node]  # (n,)
        thr = threshold[node]
        xv = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
        child = jnp.where(xv <= thr, left[node], right[node])
        return jnp.where(child >= 0, child, node)

    node = jax.lax.fori_loop(0, depth, body, node)
    return leaf_value[node]  # (n, out_dim)


_traverse_forest = jax.vmap(_traverse, in_axes=(0, 0, 0, 0, 0, None, None))


# ---------------------------------------------------------------------------
# Histogram split search (vectorized over features × bins)
# ---------------------------------------------------------------------------


def _best_gini_split(binned, y_idx, idx, n_bins, k, feat_mask,
                     min_samples_leaf):
    """Best (feature, bin, gain) under Gini impurity.

    Vectorized: per feature, a bincount over bin*k+y builds the (bins, k)
    histogram; cumulative sums give every left/right partition at once.
    """
    m = len(idx)
    d = binned.shape[1]
    sub = binned[idx]
    ys = y_idx[idx]
    best = (-1, -1, 0.0)
    total = np.bincount(ys, minlength=k).astype(np.float64)
    gini_parent = 1.0 - np.sum((total / m) ** 2)
    for j in range(d):
        if not feat_mask[j]:
            continue
        hist = np.bincount(
            sub[:, j].astype(np.int64) * k + ys, minlength=n_bins * k
        ).reshape(n_bins, k).astype(np.float64)
        left = np.cumsum(hist, axis=0)[:-1]  # (n_bins-1, k)
        ln = left.sum(1)
        rn = m - ln
        valid = (ln >= min_samples_leaf) & (rn >= min_samples_leaf)
        if not valid.any():
            continue
        with np.errstate(divide="ignore", invalid="ignore"):
            gl = 1.0 - np.sum((left / np.maximum(ln[:, None], 1)) ** 2, 1)
            right = total[None] - left
            gr = 1.0 - np.sum((right / np.maximum(rn[:, None], 1)) ** 2, 1)
        weighted = (ln * gl + rn * gr) / m
        weighted[~valid] = np.inf
        b = int(np.argmin(weighted))
        gain = gini_parent - weighted[b]
        if gain > best[2]:
            best = (j, b, float(gain))
    return best


def _best_grad_split(binned, grad, hess, idx, n_bins, feat_mask,
                     min_samples_leaf, reg_lambda):
    """Best split for gradient boosting: maximize the XGBoost-style gain
    GL²/(HL+λ) + GR²/(HR+λ) − G²/(H+λ)."""
    m = len(idx)
    d = binned.shape[1]
    sub = binned[idx]
    g = grad[idx]
    h = hess[idx]
    gtot, htot = g.sum(), h.sum()
    parent = gtot * gtot / (htot + reg_lambda)
    best = (-1, -1, 0.0)
    for j in range(d):
        if not feat_mask[j]:
            continue
        bins = sub[:, j].astype(np.int64)
        gh = np.bincount(bins, weights=g, minlength=n_bins)
        hh = np.bincount(bins, weights=h, minlength=n_bins)
        cnt = np.bincount(bins, minlength=n_bins)
        gl = np.cumsum(gh)[:-1]
        hl = np.cumsum(hh)[:-1]
        nl = np.cumsum(cnt)[:-1]
        nr = m - nl
        valid = (nl >= min_samples_leaf) & (nr >= min_samples_leaf)
        if not valid.any():
            continue
        gr_ = gtot - gl
        hr_ = htot - hl
        gain = (
            gl * gl / (hl + reg_lambda)
            + gr_ * gr_ / (hr_ + reg_lambda)
            - parent
        )
        gain[~valid] = -np.inf
        b = int(np.argmax(gain))
        if gain[b] > best[2]:
            best = (j, b, float(gain[b]))
    return best


# ---------------------------------------------------------------------------
# Greedy builder
# ---------------------------------------------------------------------------


def _build_tree(
    binned,
    edges,
    *,
    mode: str,  # "gini" | "grad"
    y_idx=None,
    k: int = 0,
    grad=None,
    hess=None,
    max_depth: int = 6,
    min_samples_split: int = 2,
    min_samples_leaf: int = 1,
    max_features: int | None = None,
    reg_lambda: float = 1.0,
    rng: np.random.Generator | None = None,
) -> _FlatTree:
    n, d = binned.shape
    n_bins = edges.shape[1] + 1
    feature, threshold, left, right, values = [], [], [], [], []

    def leaf_value(idx):
        if mode == "gini":
            counts = np.bincount(y_idx[idx], minlength=k).astype(np.float64)
            return counts / max(counts.sum(), 1)
        g, h = grad[idx].sum(), hess[idx].sum()
        return np.array([-g / (h + reg_lambda)])

    def new_node():
        feature.append(-1)
        threshold.append(0.0)
        left.append(-1)
        right.append(-1)
        values.append(None)
        return len(feature) - 1

    root = new_node()
    stack = [(root, np.arange(n), 0)]
    while stack:
        node, idx, depth = stack.pop()
        values[node] = leaf_value(idx)
        if depth >= max_depth or len(idx) < min_samples_split:
            continue
        if max_features is not None and max_features < d:
            sel = (rng or np.random.default_rng()).choice(
                d, size=max_features, replace=False
            )
            feat_mask = np.zeros(d, bool)
            feat_mask[sel] = True
        else:
            feat_mask = np.ones(d, bool)
        if mode == "gini":
            j, b, gain = _best_gini_split(
                binned, y_idx, idx, n_bins, k, feat_mask, min_samples_leaf
            )
        else:
            j, b, gain = _best_grad_split(
                binned, grad, hess, idx, n_bins, feat_mask,
                min_samples_leaf, reg_lambda,
            )
        if j < 0 or gain <= 1e-12:
            continue
        go_left = binned[idx, j] <= b
        li, ri = idx[go_left], idx[~go_left]
        if len(li) == 0 or len(ri) == 0:
            continue
        feature[node] = j
        threshold[node] = float(edges[j, b])
        lnode, rnode = new_node(), new_node()
        left[node], right[node] = lnode, rnode
        stack.append((lnode, li, depth + 1))
        stack.append((rnode, ri, depth + 1))

    out_dim = k if mode == "gini" else 1
    vals = np.zeros((len(feature), out_dim), np.float32)
    for i, v in enumerate(values):
        vals[i] = v
    return _FlatTree(
        np.maximum(np.array(feature), 0),  # -1 → 0; leaves have child=-1
        np.array(threshold),
        np.array(left),
        np.array(right),
        vals,
        max_depth,
    )


def _pad_trees(trees: list[_FlatTree]):
    """Stack flat trees into (T, max_nodes) arrays for vmapped traversal."""
    max_nodes = max(t.feature.shape[0] for t in trees)
    out_dim = trees[0].leaf_value.shape[1]

    def pad(arr, fill, dtype):
        out = np.full((len(trees), max_nodes), fill, dtype)
        for i, a in enumerate(arr):
            out[i, : a.shape[0]] = np.asarray(a)
        return jnp.asarray(out)

    feat = pad([t.feature for t in trees], 0, np.int32)
    thr = pad([t.threshold for t in trees], 0.0, np.float32)
    lft = pad([t.left for t in trees], -1, np.int32)
    rgt = pad([t.right for t in trees], -1, np.int32)
    val = np.zeros((len(trees), max_nodes, out_dim), np.float32)
    for i, t in enumerate(trees):
        val[i, : t.leaf_value.shape[0]] = np.asarray(t.leaf_value)
    return feat, thr, lft, rgt, jnp.asarray(val)


# ---------------------------------------------------------------------------
# Public estimators
# ---------------------------------------------------------------------------


@register(_MODULE)
class DecisionTreeClassifier(Estimator):
    def __init__(
        self,
        max_depth: int = 6,
        min_samples_split: int = 2,
        min_samples_leaf: int = 1,
        n_bins: int = 64,
        random_state: int = 0,
    ):
        self.max_depth = max_depth
        self.min_samples_split = min_samples_split
        self.min_samples_leaf = min_samples_leaf
        self.n_bins = n_bins
        self.random_state = random_state
        self.classes_ = None
        self._tree = None

    def fit(self, x, y):
        x = np.asarray(as_array(x, jnp.float32))
        self.classes_, y_idx = encode_classes(y)
        binned, edges = _quantize(x, self.n_bins)
        self._tree = _build_tree(
            binned,
            edges,
            mode="gini",
            y_idx=y_idx,
            k=len(self.classes_),
            max_depth=self.max_depth,
            min_samples_split=self.min_samples_split,
            min_samples_leaf=self.min_samples_leaf,
            rng=np.random.default_rng(self.random_state),
        )
        return self

    def predict_proba(self, x):
        x = as_array(x, jnp.float32)
        return _traverse(*self._tree.stacked(), x, self._tree.max_depth)

    def predict(self, x):
        probs = self.predict_proba(x)
        return self.classes_[np.asarray(jnp.argmax(probs, axis=1))]


@register(_MODULE)
class RandomForestClassifier(Estimator):
    def __init__(
        self,
        n_estimators: int = 50,
        max_depth: int = 8,
        min_samples_split: int = 2,
        min_samples_leaf: int = 1,
        max_features: str | int | None = "sqrt",
        n_bins: int = 64,
        random_state: int = 0,
    ):
        self.n_estimators = n_estimators
        self.max_depth = max_depth
        self.min_samples_split = min_samples_split
        self.min_samples_leaf = min_samples_leaf
        self.max_features = max_features
        self.n_bins = n_bins
        self.random_state = random_state
        self.classes_ = None
        self._stacked = None

    def _n_features_per_split(self, d: int) -> int | None:
        if self.max_features is None:
            return None
        if self.max_features == "sqrt":
            return max(1, int(np.sqrt(d)))
        if self.max_features == "log2":
            return max(1, int(np.log2(d)))
        return int(self.max_features)

    def fit(self, x, y):
        x = np.asarray(as_array(x, jnp.float32))
        self.classes_, y_idx = encode_classes(y)
        n, d = x.shape
        binned, edges = _quantize(x, self.n_bins)
        rng = np.random.default_rng(self.random_state)
        trees = []
        for _ in range(self.n_estimators):
            boot = rng.integers(0, n, size=n)
            trees.append(
                _build_tree(
                    binned[boot],
                    edges,
                    mode="gini",
                    y_idx=y_idx[boot],
                    k=len(self.classes_),
                    max_depth=self.max_depth,
                    min_samples_split=self.min_samples_split,
                    min_samples_leaf=self.min_samples_leaf,
                    max_features=self._n_features_per_split(d),
                    rng=rng,
                )
            )
        self._stacked = _pad_trees(trees)
        return self

    def predict_proba(self, x):
        x = as_array(x, jnp.float32)
        per_tree = _traverse_forest(*self._stacked, x, self.max_depth)
        probs = jnp.mean(per_tree, axis=0)
        return probs / jnp.maximum(jnp.sum(probs, 1, keepdims=True), 1e-12)

    def predict(self, x):
        probs = self.predict_proba(x)
        return self.classes_[np.asarray(jnp.argmax(probs, axis=1))]


@register(_MODULE)
class GradientBoostingClassifier(Estimator):
    """Histogram GBT with XGBoost-style second-order splits; binary or
    multiclass (one tree per class per round)."""

    def __init__(
        self,
        n_estimators: int = 100,
        learning_rate: float = 0.3,
        max_depth: int = 4,
        min_samples_leaf: int = 1,
        n_bins: int = 64,
        reg_lambda: float = 1.0,
        random_state: int = 0,
    ):
        self.n_estimators = n_estimators
        self.learning_rate = learning_rate
        self.max_depth = max_depth
        self.min_samples_leaf = min_samples_leaf
        self.n_bins = n_bins
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.classes_ = None
        self._stacked = None
        self._n_rounds = 0
        self._base_score = None

    def fit(self, x, y):
        x = np.asarray(as_array(x, jnp.float32))
        self.classes_, y_idx = encode_classes(y)
        k = len(self.classes_)
        n = x.shape[0]
        binned, edges = _quantize(x, self.n_bins)
        rng = np.random.default_rng(self.random_state)
        y1h = np.eye(k)[y_idx]  # (n, k)
        scores = np.zeros((n, k), np.float64)
        trees: list[_FlatTree] = []
        for _ in range(self.n_estimators):
            # softmax gradients/hessians per class
            exp = np.exp(scores - scores.max(1, keepdims=True))
            probs = exp / exp.sum(1, keepdims=True)
            grad = probs - y1h  # (n, k)
            hess = np.maximum(probs * (1.0 - probs), 1e-6)
            for c in range(k):
                tree = _build_tree(
                    binned,
                    edges,
                    mode="grad",
                    grad=grad[:, c],
                    hess=hess[:, c],
                    max_depth=self.max_depth,
                    min_samples_leaf=self.min_samples_leaf,
                    reg_lambda=self.reg_lambda,
                    rng=rng,
                )
                trees.append(tree)
                pred = np.asarray(
                    _traverse(*tree.stacked(), jnp.asarray(x),
                              tree.max_depth)
                )[:, 0]
                scores[:, c] += self.learning_rate * pred
        self._n_rounds = self.n_estimators
        self._stacked = _pad_trees(trees)
        return self

    def decision_function(self, x):
        x = as_array(x, jnp.float32)
        k = len(self.classes_)
        per_tree = _traverse_forest(*self._stacked, x, self.max_depth)
        # trees ordered round-major: (rounds*k, n, 1) → (rounds, k, n)
        per_tree = per_tree[:, :, 0].reshape(self._n_rounds, k, -1)
        return self.learning_rate * jnp.sum(per_tree, axis=0).T  # (n, k)

    def predict_proba(self, x):
        return jax.nn.softmax(self.decision_function(x), axis=-1)

    def predict(self, x):
        scores = self.decision_function(x)
        return self.classes_[np.asarray(jnp.argmax(scores, axis=1))]


@register(_MODULE)
class DecisionTreeRegressor(Estimator):
    """Squared-error regression tree (grad-mode with unit hessians)."""

    def __init__(
        self,
        max_depth: int = 6,
        min_samples_split: int = 2,
        min_samples_leaf: int = 1,
        n_bins: int = 64,
    ):
        self.max_depth = max_depth
        self.min_samples_split = min_samples_split
        self.min_samples_leaf = min_samples_leaf
        self.n_bins = n_bins
        self._tree = None
        self._mean = 0.0

    def fit(self, x, y):
        x = np.asarray(as_array(x, jnp.float32))
        y = np.asarray(as_array(y, jnp.float32)).reshape(-1)
        self._mean = float(y.mean())
        binned, edges = _quantize(x, self.n_bins)
        # Squared loss: grad = -(y - mean residual), hess = 1 → leaf values
        # become mean residuals.
        self._tree = _build_tree(
            binned,
            edges,
            mode="grad",
            grad=-(y - self._mean),
            hess=np.ones_like(y),
            max_depth=self.max_depth,
            min_samples_split=self.min_samples_split,
            min_samples_leaf=self.min_samples_leaf,
            reg_lambda=0.0,
        )
        return self

    def predict(self, x):
        x = as_array(x, jnp.float32)
        out = _traverse(*self._tree.stacked(), x, self._tree.max_depth)
        return self._mean + out[:, 0]

    def score(self, x, y):
        y = np.asarray(as_array(y, jnp.float32)).reshape(-1)
        pred = np.asarray(self.predict(x))
        ss_res = float(((y - pred) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum())
        return 1.0 - ss_res / max(ss_tot, 1e-12)
