"""Support vector machines — JAX-native ``sklearn.svm`` surface.

The reference exposes any ``sklearn.*`` class through its model service
(reference: microservices/model_image/model.py:92-162,
utils.py:151-159 signature validation); SVC/LinearSVC are the common
classifiers missing from the rest of the estimator zoo.

Design (TPU-idiomatic, not a libsvm port):
- ``LinearSVC``: primal squared-hinge objective minimised by a jitted
  ``lax.scan`` of optax-adam steps — one compiled loop, full-batch
  matmuls on the MXU, no per-step host dispatch.
- ``SVC``: kernelised via **random Fourier features** (Rahimi & Recht's
  classic RBF approximation): z(x) = sqrt(2/D)·cos(xW + b) with
  W ~ N(0, gamma·I).  The kernel trick becomes one feature matmul plus
  the same primal solver — O(n·D) instead of the O(n²) Gram matrix /
  data-dependent support-vector control flow that XLA can't tile.
  ``kernel="linear"`` skips the feature map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learningorchestra_tpu.toolkit.base import (
    Estimator,
    as_array,
    encode_classes,
)
from learningorchestra_tpu.toolkit.registry import register

_MODULE = "learningorchestra_tpu.toolkit.estimators.svm"


def _add_bias(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)


def _fit_squared_hinge(x, y_pm, n_classes, c, learning_rate, max_iter):
    """One-vs-rest squared-hinge SVM, all classes trained in one jitted
    scan (weights shape (features, classes))."""
    n, d = x.shape
    w0 = jnp.zeros((d, n_classes), jnp.float32)
    optimizer = optax.adam(learning_rate)

    def objective(w):
        margins = y_pm * (x @ w)  # (n, classes), y_pm in {-1, +1}
        hinge = jnp.maximum(0.0, 1.0 - margins)
        return 0.5 * jnp.sum(w * w) / n + c * jnp.mean(hinge ** 2)

    def step(carry, _):
        w, opt_state = carry
        loss, grads = jax.value_and_grad(objective)(w)
        updates, opt_state = optimizer.update(grads, opt_state, w)
        return (optax.apply_updates(w, updates), opt_state), loss

    (w, _), losses = jax.lax.scan(
        step, (w0, optimizer.init(w0)), None, length=max_iter
    )
    return w, losses


_fit_squared_hinge_jit = jax.jit(
    _fit_squared_hinge, static_argnames=("n_classes", "max_iter")
)


class _HingeSVMBase(Estimator):
    def __init__(self, C: float = 1.0, max_iter: int = 300,
                 learning_rate: float = 0.05, random_state: int = 0):
        self.C = C
        self.max_iter = max_iter
        self.learning_rate = learning_rate
        self.random_state = random_state
        self.coef_ = None
        self.classes_ = None

    # feature map hook (identity for the linear machine)
    def _features(self, x: jnp.ndarray) -> jnp.ndarray:
        return x

    def _init_features(self, x: jnp.ndarray) -> None:
        pass

    def fit(self, x, y):
        x = jnp.asarray(as_array(x), jnp.float32)
        self.classes_, y_idx = encode_classes(y)
        if len(self.classes_) < 2:
            raise ValueError(
                "fit needs at least 2 classes; got "
                f"{list(self.classes_)!r}"
            )
        n_classes = len(self.classes_)
        self._init_features(x)
        feats = _add_bias(self._features(x))
        onehot = jax.nn.one_hot(jnp.asarray(y_idx), n_classes)
        y_pm = 2.0 * onehot - 1.0
        self.coef_, self.losses_ = _fit_squared_hinge_jit(
            feats, y_pm, n_classes, jnp.float32(self.C),
            jnp.float32(self.learning_rate), self.max_iter,
        )
        return self

    def decision_function(self, x):
        x = jnp.asarray(as_array(x), jnp.float32)
        return _add_bias(self._features(x)) @ self.coef_

    def predict(self, x):
        idx = np.asarray(jnp.argmax(self.decision_function(x), axis=-1))
        return np.asarray(self.classes_)[idx]
    # score() inherited from Estimator — handles string labels.


@register(_MODULE)
class LinearSVC(_HingeSVMBase):
    """Primal linear SVM (squared hinge, one-vs-rest)."""


@register(_MODULE)
class SVC(_HingeSVMBase):
    """RBF-kernel SVM via random Fourier features.

    ``gamma``: "scale" (sklearn default, 1/(d·var)) or a float.
    ``n_components``: feature-map width (quality/compute trade-off).
    """

    def __init__(self, C: float = 1.0, kernel: str = "rbf",
                 gamma: str | float = "scale", n_components: int = 256,
                 max_iter: int = 300, learning_rate: float = 0.05,
                 random_state: int = 0):
        super().__init__(C=C, max_iter=max_iter,
                         learning_rate=learning_rate,
                         random_state=random_state)
        if kernel not in ("rbf", "linear"):
            raise ValueError(f"unsupported kernel: {kernel!r}")
        self.kernel = kernel
        self.gamma = gamma
        self.n_components = n_components
        self._w = None
        self._b = None

    def _init_features(self, x: jnp.ndarray) -> None:
        if self.kernel == "linear":
            return
        d = x.shape[1]
        if self.gamma == "scale":
            var = float(jnp.var(x))
            gamma = 1.0 / (d * var) if var > 0 else 1.0 / d
        else:
            gamma = float(self.gamma)
        key = jax.random.PRNGKey(self.random_state)
        kw, kb = jax.random.split(key)
        self._w = jax.random.normal(
            kw, (d, self.n_components), jnp.float32
        ) * jnp.sqrt(2.0 * gamma)
        self._b = jax.random.uniform(
            kb, (self.n_components,), jnp.float32, 0.0, 2.0 * jnp.pi
        )

    def _features(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.kernel == "linear":
            return x
        proj = x @ self._w + self._b
        return jnp.sqrt(2.0 / self.n_components) * jnp.cos(proj)
