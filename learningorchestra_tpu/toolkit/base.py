"""Estimator protocol and array coercion helpers.

Estimators expose the sklearn-style surface the reference drives by
reflection — ``getattr(instance, method)(**treated_params)`` with
``inspect.signature`` validation (reference:
microservices/binary_executor_image/binary_execution.py:188-200,
utils.py:142-188) — so the executor layer works identically here.
"""

from __future__ import annotations

import inspect
from typing import Any

import jax.numpy as jnp
import numpy as np


def as_array(x: Any, dtype=None) -> jnp.ndarray:
    """Coerce DataFrames / lists / numpy / jax arrays to a jnp array.

    Dataset artifacts load as pandas DataFrames (the reference's convention
    — Mongo collection → pd.DataFrame, binary_executor_image/
    utils.py:322-330); numeric coercion happens here at the toolkit edge.
    """
    if hasattr(x, "to_numpy"):  # pandas DataFrame / Series
        x = x.to_numpy()
    arr = np.asarray(x)
    if arr.dtype == object:
        arr = arr.astype(np.float32)
    out = jnp.asarray(arr)
    if dtype is not None:
        out = out.astype(dtype)
    return out


def as_labels(y: Any) -> jnp.ndarray:
    """Coerce labels to an int32 vector, mapping arbitrary class values to
    contiguous ids; returns the array (classes kept by the caller)."""
    if hasattr(y, "to_numpy"):
        y = y.to_numpy()
    arr = np.asarray(y).reshape(-1)
    return jnp.asarray(arr)


def encode_classes(y: Any) -> tuple[np.ndarray, np.ndarray]:
    """(classes, encoded int ids) — np.unique inverse mapping."""
    if hasattr(y, "to_numpy"):
        y = y.to_numpy()
    arr = np.asarray(y).reshape(-1)
    classes, inv = np.unique(arr, return_inverse=True)
    return classes, inv.astype(np.int32)


class Estimator:
    """Base class: get_params/set_params over __init__ kwargs, repr."""

    def get_params(self) -> dict:
        sig = inspect.signature(type(self).__init__)
        return {
            name: getattr(self, name)
            for name in sig.parameters
            if name != "self" and hasattr(self, name)
        }

    def set_params(self, **params) -> "Estimator":
        for key, val in params.items():
            setattr(self, key, val)
        return self

    def __repr__(self) -> str:
        params = ", ".join(f"{k}={v!r}" for k, v in self.get_params().items())
        return f"{type(self).__name__}({params})"

    # Classification scorer shared by classifiers.
    def score(self, x, y) -> float:
        import numpy as np

        preds = np.asarray(self.predict(x)).reshape(-1)
        truth = np.asarray(y if not hasattr(y, "to_numpy") else y.to_numpy())
        truth = truth.reshape(-1)
        return float((preds == truth).mean())
