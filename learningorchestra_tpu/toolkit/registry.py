"""Constructor registry with reference-path aliasing.

Maps ``(module_path, class_name)`` request pairs to JAX-native factories.
The reference validates ``modulePath`` by importing it and checking the
class exists with ``inspect`` (reference:
microservices/model_image/utils.py:151-159); here validity means "the pair
is registered", and reference-era module paths alias to the native ones so
a client that posts ``{"modulePath": "sklearn.linear_model", "class":
"LogisticRegression"}`` transparently gets the JAX estimator.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

from learningorchestra_tpu.concurrency_rt import make_lock

_lock = make_lock("registry._lock")
_registry: dict[tuple[str, str], Callable] = {}
_loaded = False

# Reference-style module path → native module path.
MODULE_ALIASES = {
    "sklearn.linear_model": "learningorchestra_tpu.toolkit.estimators.linear",
    "sklearn.ensemble": "learningorchestra_tpu.toolkit.estimators.trees",
    "sklearn.tree": "learningorchestra_tpu.toolkit.estimators.trees",
    "sklearn.naive_bayes": "learningorchestra_tpu.toolkit.estimators.bayes",
    "sklearn.cluster": "learningorchestra_tpu.toolkit.estimators.cluster",
    "sklearn.decomposition":
        "learningorchestra_tpu.toolkit.estimators.decomposition",
    "sklearn.manifold":
        "learningorchestra_tpu.toolkit.estimators.decomposition",
    "sklearn.preprocessing":
        "learningorchestra_tpu.toolkit.estimators.preprocessing",
    "sklearn.neighbors": "learningorchestra_tpu.toolkit.estimators.neighbors",
    "sklearn.svm": "learningorchestra_tpu.toolkit.estimators.svm",
    "tensorflow.keras.applications": "learningorchestra_tpu.models.vision",
    "tensorflow.keras.models": "learningorchestra_tpu.models",
    "torch.nn": "learningorchestra_tpu.models",
}


class RegistryError(KeyError):
    pass


def register(
    module_path: str, class_name: str | None = None
) -> Callable[[Callable], Callable]:
    """Class decorator: ``@register("learningorchestra_tpu.toolkit...")``."""

    def deco(cls: Callable) -> Callable:
        name = class_name or cls.__name__
        with _lock:
            _registry[(module_path, name)] = cls
        return cls

    return deco


def _ensure_loaded() -> None:
    """Import all implementation modules once so decorators run."""
    global _loaded
    with _lock:
        if _loaded:
            return
        _loaded = True
    import importlib

    for mod in (
        "learningorchestra_tpu.toolkit.estimators.linear",
        "learningorchestra_tpu.toolkit.estimators.trees",
        "learningorchestra_tpu.toolkit.estimators.bayes",
        "learningorchestra_tpu.toolkit.estimators.cluster",
        "learningorchestra_tpu.toolkit.estimators.decomposition",
        "learningorchestra_tpu.toolkit.estimators.preprocessing",
        "learningorchestra_tpu.toolkit.estimators.neighbors",
        "learningorchestra_tpu.toolkit.estimators.svm",
        "learningorchestra_tpu.models.mlp",
        "learningorchestra_tpu.models.vision",
        "learningorchestra_tpu.models.text",
        "learningorchestra_tpu.models.longcontext",
    ):
        importlib.import_module(mod)


def resolve(module_path: str, class_name: str) -> Callable:
    """Look up a factory; reference-era paths go through MODULE_ALIASES."""
    _ensure_loaded()
    native = MODULE_ALIASES.get(module_path, module_path)
    with _lock:
        factory = _registry.get((native, class_name))
    if factory is None:
        raise RegistryError(
            f"unknown model/estimator: modulePath={module_path!r} "
            f"class={class_name!r}"
        )
    return factory


def exists(module_path: str, class_name: str) -> bool:
    try:
        resolve(module_path, class_name)
        return True
    except RegistryError:
        return False


def validate_init_params(
    module_path: str, class_name: str, params: dict
) -> list[str]:
    """Names in ``params`` not accepted by the constructor — the
    reference's signature check (model_image/utils.py:151-159) returning
    the offending keys instead of a bare boolean."""
    factory = resolve(module_path, class_name)
    sig = inspect.signature(factory.__init__)
    accepted = set(sig.parameters) - {"self"}
    if any(
        p.kind == inspect.Parameter.VAR_KEYWORD
        for p in sig.parameters.values()
    ):
        return []
    return [k for k in params if k not in accepted]


def validate_method(class_or_factory: Any, method: str) -> bool:
    """Method-exists check (reference: binary_executor_image/
    utils.py:152-165 via inspect.getmembers)."""
    return callable(getattr(class_or_factory, method, None))


def validate_method_params(
    class_or_factory: Any, method: str, params: dict
) -> list[str]:
    fn = getattr(class_or_factory, method, None)
    if fn is None:
        return list(params)
    sig = inspect.signature(fn)
    if any(
        p.kind == inspect.Parameter.VAR_KEYWORD
        for p in sig.parameters.values()
    ):
        return []
    accepted = set(sig.parameters) - {"self"}
    return [k for k in params if k not in accepted]


def constructors() -> dict[str, Callable]:
    """class_name → factory map (for the ``#`` spec namespace)."""
    _ensure_loaded()
    with _lock:
        return {name: fac for (_, name), fac in _registry.items()}


def list_registered() -> list[dict]:
    _ensure_loaded()
    with _lock:
        return [
            {"modulePath": mod, "class": name}
            for (mod, name) in sorted(_registry)
        ]
