"""Toolkit layer: the JAX-native model/estimator registry.

The reference instantiates "any class from a whitelisted importable module"
— ``sklearn.*``, ``tensorflow.keras.applications.*`` — inside its model
service (reference: microservices/model_image/model.py:92-162,
utils.py:151-159).  Here the same request shape (``modulePath`` +
``class`` + ``classParameters``) resolves against a registry of JAX-native
implementations: Flax neural models compiled by XLA to TPU and classical
estimators re-implemented on jax.numpy.  Reference-style module paths
(``sklearn.linear_model``, ``tensorflow.keras.applications``) are accepted
as aliases so existing client pipelines keep working.
"""

from learningorchestra_tpu.toolkit import registry
from learningorchestra_tpu.toolkit.base import Estimator, as_array

__all__ = ["registry", "Estimator", "as_array"]
