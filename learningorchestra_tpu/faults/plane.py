"""Process-wide, seeded fault-injection plane.

The reference system's core robustness claim is that any pipeline step
can fail and be re-executed independently (PAPER.md, README.md:7) — but
neither the reference nor this reproduction had a way to *prove* it
short of ad-hoc monkeypatching.  On TPU the claim matters more, not
less: preemption is routine (the pjit/TPUv4 scaling paper treats
restart-and-resume as a first-class part of training at scale), so the
recovery machinery — preemption retries, checkpoint resume, lease
timeouts, deadlines — needs to be exercisable on demand, in tests, in
CI, and against a staging deployment.

This module is that switchboard.  Subsystems declare **named fault
points** and call :func:`hit` on their hot paths:

====================  =======================================================
point                 call site
====================  =======================================================
``engine.dispatch``   jobs/engine.py — start of every job-body attempt
``lease.acquire``     jobs/leases.py — entry of every chip-lease request
``compile.build``     train/compile_cache.py — before a miss traces/compiles
``store.wal_write``   store/document_store.py — before every WAL append
``serve.apply``       serve/service.py — before a coalesced batch dispatch
``serve.route``       serve/fleet/router.py — every fleet routing decision
``http.handler``      api/server.py — before every admitted route handler
``train.epoch``       train/neural.py — top of every fit epoch
``replica.wal_ship``  store/replica.py — entry of every WAL-shipping sync
``store.ha.failover`` store/ha.py — entry of a standby's promotion
``cluster.claim``     jobs/cluster.py — before every dispatch claim CAS
``cluster.heartbeat`` jobs/cluster.py — entry of every lease renewal
``cluster.steal``     jobs/cluster.py — before an expired-claim takeover
====================  =======================================================

A **schedule** arms a point with one of three behaviors:

- ``preempt`` — raise :class:`jobs.engine.Preempted` (the structured
  TPU-preemption signal the engine's retry loop consumes);
- ``error``   — raise :class:`FaultInjected` (an ordinary crash);
- ``delay``   — sleep ``delay_ms`` (latency injection, no exception).

Schedules are **deterministic and seeded**: ``rate < 1`` draws from a
``random.Random`` seeded with ``seed`` mixed with a stable CRC of the
point name (never the process-salted ``hash()``), so the same
(seed, rate) arms the same trigger pattern on every run — chaos tests
are reproducible, not flaky.  ``after`` skips the first N hits and
``max_triggers`` bounds total firings, so "preempt the 3rd epoch once"
is one schedule, not a monkeypatch.

Configuration: ``LO_TPU_FAULT_<POINT>`` environment variables (see
:func:`load_env`) and the REST surface (``GET/POST/DELETE /faults`` in
api/server.py).  Every trigger increments
``lo_fault_triggers_total{point,mode}`` in the obs registry and the
plane's own per-point counters (served by :func:`status`).

Zero-cost disabled path: :func:`hit` is a truthiness check on an empty
module-level dict and a return — no lock, no lookup, no allocation.
bench.py's ``_faults_probe`` pins the number.
"""

from __future__ import annotations

import time
import zlib

from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.log import get_logger, kv

logger = get_logger("faults")

__all__ = [
    "ENV_PREFIX",
    "FaultInjected",
    "FaultSchedule",
    "MODES",
    "POINTS",
    "arm",
    "disarm",
    "disarm_all",
    "hit",
    "load_env",
    "points",
    "register_point",
    "status",
]

#: Modes a schedule can arm a point with.
MODES = ("preempt", "error", "delay")

#: The built-in fault points.  Subsystems adding a new point register it
#: with :func:`register_point`; the test gate in tests/test_faults.py
#: fails any registered point without a chaos driver.
POINTS = (
    "engine.dispatch",
    "lease.acquire",
    "compile.build",
    "store.wal_write",
    "serve.apply",
    "serve.route",
    "serve.decode_step",
    "http.handler",
    "train.epoch",
    "replica.wal_ship",
    "store.ha.failover",
    "cache.aot_load",
    "cache.aot_store",
    "cluster.claim",
    "cluster.heartbeat",
    "cluster.steal",
)


class FaultInjected(Exception):
    """The injected failure for ``error`` mode — deliberately an
    ordinary exception: recovery paths must treat it like any crash."""


class FaultSchedule:
    """One point's armed behavior: deterministic, seeded, bounded."""

    def __init__(self, point: str, mode: str, *, rate: float = 1.0,
                 seed: int = 0, after: int = 0, max_triggers: int = 0,
                 delay_ms: float = 0.0):
        if mode not in MODES:
            raise ValueError(
                f"unknown fault mode {mode!r} (one of {MODES})"
            )
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"rate must be in (0, 1], got {rate!r}")
        if delay_ms < 0:
            raise ValueError(f"delay_ms must be >= 0, got {delay_ms!r}")
        self.point = point
        self.mode = mode
        self.rate = float(rate)
        self.seed = int(seed)
        self.after = max(0, int(after))
        self.max_triggers = max(0, int(max_triggers))  # 0 = unbounded
        self.delay_ms = float(delay_ms)
        self.hits = 0
        self.triggers = 0
        # Stable per-(seed, point) stream: zlib.crc32, NOT hash() —
        # Python salts str hashes per process, which would make "the
        # same seed" mean different trigger patterns across runs.
        self._rng = _random().Random(
            (self.seed << 32) ^ zlib.crc32(point.encode())
        )

    def should_fire(self) -> bool:
        """One hit's verdict.  Caller holds the plane lock — the
        hit/trigger counters and the RNG stream must be serialized for
        the schedule to stay deterministic under concurrency."""
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.max_triggers and self.triggers >= self.max_triggers:
            return False
        if self.rate < 1.0 and self._rng.random() >= self.rate:
            return False
        self.triggers += 1
        return True

    def to_doc(self) -> dict:
        return {
            "mode": self.mode,
            "rate": self.rate,
            "seed": self.seed,
            "after": self.after,
            "maxTriggers": self.max_triggers,
            "delayMs": self.delay_ms,
            "hits": self.hits,
            "triggers": self.triggers,
        }


def _random():
    import random

    return random


_LOCK = make_lock("plane._LOCK")
#: point -> FaultSchedule.  THE fast-path gate: empty means the whole
#: plane is disabled and :func:`hit` returns after one truthiness check.
_ARMED: dict[str, FaultSchedule] = {}
#: Registered point names (built-ins + register_point additions).
_POINTS: set[str] = set(POINTS)
#: Cumulative per-point counters, surviving disarm — the test gate and
#: post-chaos assertions read these.
_TOTALS: dict[str, dict] = {}


def register_point(name: str) -> str:
    """Declare a fault point (idempotent); returns ``name`` so call
    sites can do ``POINT = register_point("x.y")``."""
    with _LOCK:
        _POINTS.add(name)
    return name


def points() -> tuple:
    with _LOCK:
        return tuple(sorted(_POINTS))


def _canonical(name: str) -> str:
    """Resolve a point name case/separator-insensitively (the env-var
    spelling ``ENGINE_DISPATCH`` must find ``engine.dispatch`` even
    though ``store.wal_write`` itself contains an underscore)."""
    with _LOCK:
        if name in _POINTS:
            return name
        folded = name.casefold().replace(".", "_")
        for point in _POINTS:
            if point.casefold().replace(".", "_") == folded:
                return point
    raise ValueError(
        f"unknown fault point {name!r} (known: {sorted(_POINTS)})"
    )


def arm(point: str, mode: str, *, rate: float = 1.0, seed: int = 0,
        after: int = 0, max_triggers: int = 0,
        delay_ms: float = 0.0) -> dict:
    """Arm ``point`` with a fresh schedule (replacing any existing one);
    returns the schedule's JSON doc."""
    point = _canonical(point)
    sched = FaultSchedule(
        point, mode, rate=rate, seed=seed, after=after,
        max_triggers=max_triggers, delay_ms=delay_ms,
    )
    with _LOCK:
        _ARMED[point] = sched
    logger.warning(kv(event="fault_armed", point=point, mode=mode,
                      rate=rate, seed=seed, after=after,
                      max=max_triggers))
    return sched.to_doc()


def disarm(point: str) -> bool:
    point = _canonical(point)
    with _LOCK:
        sched = _ARMED.pop(point, None)
        if sched is not None:
            _accumulate_locked(sched)
    return sched is not None


def disarm_all() -> None:
    with _LOCK:
        for sched in _ARMED.values():
            _accumulate_locked(sched)
        _ARMED.clear()


def _accumulate_locked(sched: FaultSchedule) -> None:
    tot = _TOTALS.setdefault(
        sched.point, {"hits": 0, "triggers": 0}
    )
    tot["hits"] += sched.hits
    tot["triggers"] += sched.triggers
    sched.hits = sched.triggers = 0


def reset() -> None:
    """Disarm everything and zero the cumulative counters (tests)."""
    with _LOCK:
        _ARMED.clear()
        _TOTALS.clear()


def status() -> dict:
    """The REST surface's GET body: every registered point with its
    armed schedule (if any) and cumulative hit/trigger counts."""
    with _LOCK:
        out = {}
        for point in sorted(_POINTS):
            tot = _TOTALS.get(point, {"hits": 0, "triggers": 0})
            sched = _ARMED.get(point)
            out[point] = {
                "armed": sched.to_doc() if sched is not None else None,
                "hits": tot["hits"] + (sched.hits if sched else 0),
                "triggers": tot["triggers"]
                + (sched.triggers if sched else 0),
            }
        return {"enabled": bool(_ARMED), "points": out}


def triggers(point: str) -> int:
    """Cumulative trigger count for one point (armed + disarmed)."""
    point = _canonical(point)
    with _LOCK:
        n = _TOTALS.get(point, {}).get("triggers", 0)
        sched = _ARMED.get(point)
        return n + (sched.triggers if sched is not None else 0)


def hit(point: str) -> None:
    """The per-site probe.  DISABLED PATH MUST STAY FREE: one
    truthiness check on a module global, then return — this line runs
    on every WAL append and every HTTP dispatch."""
    if not _ARMED:
        return
    _fire(point)


def _fire(point: str) -> None:
    with _LOCK:
        sched = _ARMED.get(point)
        if sched is None or not sched.should_fire():
            return
        mode = sched.mode
        delay_ms = sched.delay_ms
        trigger_n = sched.triggers
    _trigger_counter().inc(point=point, mode=mode)
    from learningorchestra_tpu.obs import flight as obs_flight

    obs_flight.record(
        "faults", "trigger", point=point, mode=mode, n=trigger_n,
    )
    logger.warning(kv(event="fault_triggered", point=point, mode=mode,
                      trigger=trigger_n))
    if mode == "delay":
        time.sleep(delay_ms / 1e3)
        return
    if mode == "preempt":
        from learningorchestra_tpu.jobs.engine import Preempted

        raise Preempted(f"injected preemption at {point!r}")
    raise FaultInjected(f"injected fault at {point!r}")


def _trigger_counter():
    """Obs-registry counter, resolved per trigger so a registry reset
    (tests, the bench's on/off probe) takes effect immediately —
    triggers are rare, the lookup cost is irrelevant."""
    from learningorchestra_tpu.obs.metrics import get_registry

    return get_registry().counter(
        "lo_fault_triggers_total",
        "Injected faults fired, by point and mode.",
        labels=("point", "mode"),
    )


def parse_spec(spec: str) -> dict:
    """``"mode[:k=v,...]"`` → arm() kwargs.  The env-var grammar::

        LO_TPU_FAULT_ENGINE_DISPATCH="preempt:rate=0.5,seed=7,max=2"
        LO_TPU_FAULT_SERVE_APPLY="delay:ms=50"
        LO_TPU_FAULT_STORE_WAL_WRITE="error:rate=0.01,seed=1,after=100"

    Keys: ``rate``, ``seed``, ``after``, ``max`` (max_triggers),
    ``ms`` (delay_ms).  Unknown keys are rejected loudly — a typo'd
    chaos knob silently doing nothing would fake a green drill.
    """
    mode, _, rest = spec.strip().partition(":")
    mode = mode.strip().lower()
    if mode not in MODES:
        raise ValueError(
            f"bad fault spec {spec!r}: mode must be one of {MODES}"
        )
    kw: dict = {"mode": mode}
    keymap = {"rate": ("rate", float), "seed": ("seed", int),
              "after": ("after", int), "max": ("max_triggers", int),
              "ms": ("delay_ms", float)}
    for tok in filter(None, (t.strip() for t in rest.split(","))):
        key, eq, val = tok.partition("=")
        if not eq or key.strip() not in keymap:
            raise ValueError(
                f"bad fault spec {spec!r}: token {tok!r} (keys: "
                f"{sorted(keymap)})"
            )
        name, cast = keymap[key.strip()]
        kw[name] = cast(val.strip())
    return kw


ENV_PREFIX = "LO_TPU_FAULT_"


def load_env(env=None) -> list[str]:
    """Arm every ``LO_TPU_FAULT_<POINT>=<spec>`` found in ``env``
    (default ``os.environ``); returns the armed point names.  Called at
    API-server construction so a deployment can boot straight into a
    chaos drill.  Bad specs raise — same loud-rejection contract as
    the config tree's boolean env knobs."""
    import os

    env = os.environ if env is None else env
    armed = []
    for key, raw in env.items():
        if not key.startswith(ENV_PREFIX) or not raw.strip():
            continue
        kw = parse_spec(raw)
        doc_point = _canonical(key[len(ENV_PREFIX):])
        arm(doc_point, kw.pop("mode"), **kw)
        armed.append(doc_point)
    return armed
