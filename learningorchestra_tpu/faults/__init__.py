"""Fault-injection plane (chaos layer) — see plane.py.

Subsystems call ``faults.hit("<point>")`` on their hot paths; seeded
schedules armed via ``LO_TPU_FAULT_*`` env or the ``/faults`` REST
surface decide whether that hit preempts, errors, or delays.  Disabled
(the default) it is one truthiness check.
"""

from learningorchestra_tpu.faults.plane import (
    ENV_PREFIX,
    MODES,
    POINTS,
    FaultInjected,
    FaultSchedule,
    arm,
    disarm,
    disarm_all,
    hit,
    load_env,
    parse_spec,
    points,
    register_point,
    reset,
    status,
    triggers,
)

__all__ = [
    "ENV_PREFIX",
    "MODES",
    "POINTS",
    "FaultInjected",
    "FaultSchedule",
    "arm",
    "disarm",
    "disarm_all",
    "hit",
    "load_env",
    "parse_spec",
    "points",
    "register_point",
    "reset",
    "status",
    "triggers",
]
