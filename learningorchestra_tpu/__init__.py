"""learningorchestra_tpu — a TPU-native ML pipeline-orchestration framework.

A ground-up, TPU-first re-design of the capabilities of
joaoderocha/learningOrchestra (reference mounted at /root/reference): a
REST-fronted system where every step of an ML pipeline — dataset ingest,
transform, explore, model, tune, train, evaluate, predict, arbitrary
functions, whole-pipeline builders — runs as an asynchronous, stateful,
individually re-executable job over named, lineage-tracked artifacts.

Where the reference wires Flask microservices to Scikit-learn / TensorFlow /
Spark MLlib containers and distributes training with Horovod-on-Ray (Gloo
ring-allreduce), this framework is designed for TPUs from the start:

- compute is JAX/XLA: jitted train loops, Flax model zoo, JAX-native
  classical estimators (no sklearn/TF on the hot path);
- data parallelism is a sharding annotation (`pjit` / `shard_map` over a
  `jax.sharding.Mesh`), with XLA emitting ICI collectives — replacing the
  reference's host-side Horovod ring (reference:
  microservices/binary_executor_image/training_function/train_function.py);
- long-context is first-class: ring attention over a sequence mesh axis;
- multi-host runs over DCN via `jax.distributed.initialize`, orchestrated by
  the framework's own coordinator instead of Ray
  (reference: microservices/binary_executor_image/server.py:13-17);
- artifacts keep the reference's contract — named collections whose document
  `_id=0` is the metadata record with `finished` + lineage
  (reference: microservices/database_api_image/utils.py:50-63) — but are
  stored in an embedded, thread-safe document store instead of MongoDB.
"""

__version__ = "0.1.0"

from learningorchestra_tpu.config import Config, get_config, set_config

__all__ = ["Config", "get_config", "set_config", "__version__"]
