"""Per-job accelerator placement: device leases.

The reference isolates concurrent compute with Spark FAIR-scheduler
pools and Ray placement groups (reference:
builder_image/fairscheduler.xml:1-7, binary_executor_image/server.py:16
— ``RayExecutor.create_settings(placement_group_timeout_s=120)``).
Round 1 ran every job against the same default device with no placement
(VERDICT r1 weak item 4): concurrent TPU fits would contend for HBM and
interleave on one chip.

``DeviceLeaser`` is the TPU-native equivalent: accelerator chips are
lease units; a job that runs device compute takes a lease for the
duration of its on-device work, so

- accelerator jobs SERIALIZE per chip (or take disjoint chips when the
  host has several);
- host-only (classical estimator / IO) jobs never lease and stay fully
  concurrent;
- the lease is recorded in the job's metadata document, making
  placement observable through the ordinary GET/poll contract.

On CPU-only backends leasing is a no-op (there is no chip to contend
for; XLA:CPU interleaves fine) unless a device list is injected, which
is how the unit tests exercise the serialization property.
"""

from __future__ import annotations

import contextlib
import time
from typing import Sequence

from learningorchestra_tpu import faults
from learningorchestra_tpu.concurrency_rt import make_condition, make_lock
from learningorchestra_tpu.log import get_logger, kv
from learningorchestra_tpu.obs import tracing

logger = get_logger("leases")

DEFAULT_LEASE_TIMEOUT_S = 120.0  # reference parity: placement timeout


def _lease_metrics():
    """Lease instrumentation handles (obs/metrics.py), resolved per
    lease so registry resets take effect immediately."""
    from learningorchestra_tpu.obs.metrics import get_registry

    reg = get_registry()
    return (
        reg.histogram(
            "lo_lease_wait_seconds",
            "Time a job waited for its chip lease.",
            buckets=(0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0,
                     300.0, 1800.0),
        ),
        reg.histogram(
            "lo_lease_hold_seconds",
            "Time a job held its chip lease.",
            buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0,
                     7200.0, 43200.0),
        ),
        reg.counter(
            "lo_leases_total",
            "Chip leases granted.",
        ),
    )


class LeaseTimeout(Exception):
    pass


class DeviceLeaser:
    """Blocking lease manager over a fixed set of accelerator devices."""

    def __init__(self, device_ids: Sequence[str] | None = None):
        self._cv = make_condition("DeviceLeaser._cv")
        self._explicit = list(device_ids) if device_ids is not None else None
        self._free: list[str] | None = None
        self._all: list[str] = []
        # (label, device, t_start, t_end) — placement audit trail; tests
        # assert non-overlap per device from it.  Bounded: a long-lived
        # server must not accumulate one tuple per job forever.
        import collections

        self.history: collections.deque = collections.deque(maxlen=1024)
        # Live leases, for the deadline watchdog's revoke path: each
        # record is {label, devices, revoked} — ``revoked`` devices
        # were force-returned to the pool and must NOT be re-freed
        # when the (possibly zombie) holder's with-block finally runs.
        self._active: list[dict] = []

    def _ensure_devices(self) -> None:
        if self._free is not None:
            return
        if self._explicit is not None:
            self._all = list(self._explicit)
        else:
            import jax

            try:
                devs = jax.devices()
            except Exception:
                devs = []
            if devs and devs[0].platform != "cpu":
                self._all = [f"{d.platform}:{d.id}" for d in devs]
            else:
                self._all = []  # CPU backend: leasing is a no-op
        self._free = list(self._all)

    @property
    def device_count(self) -> int:
        with self._cv:
            self._ensure_devices()
            return len(self._all)

    def snapshot(self) -> dict:
        """Lock-consistent view for dashboards: does NOT force device
        discovery (``initialized`` False until the first lease), since
        discovery may block on remote hardware."""
        with self._cv:
            return {
                "initialized": self._free is not None,
                "free": list(self._free or ()),
                "all": list(self._all),
                "recent": list(self.history)[-10:],
            }

    @contextlib.contextmanager
    def lease(
        self,
        n_devices: int = 1,
        *,
        label: str = "",
        timeout: float | None = None,
    ):
        """Hold ``n_devices`` accelerator devices for the with-block.

        ``n_devices <= 0`` means "all devices" (a distributed fit spans
        the host's whole slice).  Yields the leased device ids — empty
        on CPU-only backends, where the block runs unplaced.

        ``timeout=None`` (the default, used by the job services) WAITS
        — a queued job behind a long training run must queue, not fail;
        the job engine's pool bounds how many can wait.  Pass a finite
        timeout to get ``LeaseTimeout`` instead (the reference's 120 s
        placement-timeout semantics).
        """
        t_req = time.monotonic()
        # Chaos probe: an armed schedule can delay every lease request
        # (contention drills) or fail it outright — the injected error
        # flows to the job body exactly as a real placement failure.
        faults.hit("lease.acquire")
        with self._cv:
            self._ensure_devices()
            if not self._all:
                taken: list[str] = []
            else:
                want = len(self._all) if n_devices <= 0 else min(
                    n_devices, len(self._all)
                )
                deadline = (
                    None if timeout is None
                    else time.monotonic() + timeout
                )
                while len(self._free) < want:
                    if deadline is None:
                        self._cv.wait()
                        continue
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise LeaseTimeout(
                            f"no {want}-device lease within {timeout}s "
                            f"(job {label!r})"
                        )
                    self._cv.wait(remaining)
                taken = [self._free.pop() for _ in range(want)]
        t0 = time.monotonic()
        rec = {"label": label, "devices": list(taken),
               "revoked": set()}
        if taken:
            with self._cv:
                self._active.append(rec)
            wait_hist, hold_hist, leases_total = _lease_metrics()
            wait_hist.observe(t0 - t_req)
            leases_total.inc()
            logger.info(kv(event="lease", job=label, devices=taken))
        try:
            if taken:
                # The span covers the whole with-block, so compile and
                # per-epoch spans recorded inside nest under it.
                with tracing.span(
                    "lease",
                    devices=",".join(taken),
                    waitS=round(t0 - t_req, 6),
                ):
                    yield taken
            else:
                yield taken
        finally:
            t1 = time.monotonic()
            with self._cv:
                for dev in taken:
                    if dev in rec["revoked"]:
                        # The deadline watchdog already returned this
                        # device to the pool; re-freeing it here would
                        # double-count it.
                        continue
                    self._free.append(dev)
                    self.history.append((label, dev, t0, t1))
                if taken:
                    try:
                        self._active.remove(rec)
                    except ValueError:
                        pass
                self._cv.notify_all()
            if taken:
                hold_hist.observe(t1 - t0)
                logger.info(kv(
                    event="release", job=label, devices=taken,
                    held=f"{t1 - t0:.2f}s",
                ))

    def acquire(
        self,
        n_devices: int = 1,
        *,
        label: str = "",
        timeout: float | None = None,
    ) -> "LeaseHandle":
        """Non-context lease for LONG-LIVED holders — a serving-fleet
        replica keeps its chip for the replica's lifetime, which has no
        with-block: the acquiring thread (a REST handler or the
        autoscaler's first scale-up) is never the releasing thread (the
        autoscaler's scale-down, or service shutdown).

        Returns a :class:`LeaseHandle`; call ``release()`` exactly once
        (idempotent).  Same blocking/timeout semantics as
        :meth:`lease`.  The with-block's trace span is suppressed: a
        span opened in the acquiring thread could not legally close in
        the releasing one (contextvar tokens are thread-bound), and a
        replica's multi-hour hold is lease-history/metrics material,
        not a job-trace interval.
        """
        from learningorchestra_tpu.obs import tracing

        cm = self.lease(n_devices, label=label, timeout=timeout)
        with tracing.activate(None):
            devices = cm.__enter__()
        return LeaseHandle(cm, list(devices))

    def revoke(self, label: str) -> list[str]:
        """Force-release every device held by leases labelled
        ``label`` or ``label:*`` (a tune job's trials lease as
        ``<job>:trial``) — the deadline watchdog's reclaim path.

        The holder's thread may still be RUNNING device work; on real
        hardware the next lessee contends with the zombie until it
        dies.  That is the honest limit of a thread model (the
        reference's running job dies only with its container) — the
        deadline's guarantee is that the SCHEDULER stops waiting, not
        that the computation stops.
        """
        freed: list[str] = []
        t1 = time.monotonic()
        with self._cv:
            for rec in self._active:
                if rec["label"] != label and not \
                        rec["label"].startswith(label + ":"):
                    continue
                for dev in rec["devices"]:
                    if dev in rec["revoked"]:
                        continue
                    rec["revoked"].add(dev)
                    self._free.append(dev)
                    self.history.append((rec["label"], dev, t1, t1))
                    freed.append(dev)
            if freed:
                self._cv.notify_all()
        if freed:
            logger.warning(kv(event="revoke", job=label, devices=freed))
        return freed


class LeaseHandle:
    """A held lease detached from its with-block (see
    :meth:`DeviceLeaser.acquire`).  ``devices`` is the granted id list
    (empty on CPU-only backends).  ``release()`` is idempotent and may
    run on any thread."""

    __slots__ = ("devices", "_cm", "_lock", "_released")

    def __init__(self, cm, devices: list[str]):
        self._cm = cm
        self.devices = devices
        self._lock = make_lock("LeaseHandle._lock")
        self._released = False

    def release(self) -> None:
        from learningorchestra_tpu.obs import tracing

        with self._lock:
            if self._released:
                return
            self._released = True
        # Resume the suspended lease generator with no active trace:
        # its span fast-path must stay the no-token branch it took at
        # acquire time (a different thread cannot reset another
        # thread's contextvar token).
        with tracing.activate(None):
            self._cm.__exit__(None, None, None)


def jax_device_for(device_id: str):
    """Resolve a lease's device id ("tpu:3") back to the jax.Device —
    the placement step: a job that leased chip k must actually RUN on
    chip k (``jax.default_device``), not on whatever device 0 is."""
    import jax

    try:
        platform, idx = device_id.rsplit(":", 1)
        for d in jax.devices():
            if d.platform == platform and d.id == int(idx):
                return d
    except Exception:  # noqa: BLE001 — placement is best-effort
        return None
    return None
