"""Async job engine — replaces the reference's per-service
``ThreadPoolExecutor.submit(__pipeline)`` pattern (e.g. reference:
microservices/binary_executor_image/binary_execution.py:139,155-186)."""

from learningorchestra_tpu.jobs.engine import (
    JobDeadlineExceeded,
    JobEngine,
    JobState,
    Preempted,
    current_attempt,
)

__all__ = [
    "JobDeadlineExceeded",
    "JobEngine",
    "JobState",
    "Preempted",
    "current_attempt",
]
