"""Async job engine — replaces the reference's per-service
``ThreadPoolExecutor.submit(__pipeline)`` pattern (e.g. reference:
microservices/binary_executor_image/binary_execution.py:139,155-186)."""

from learningorchestra_tpu.jobs.cancel import (
    CancelToken,
    cancel_requested,
    current_cancel_token,
)
from learningorchestra_tpu.jobs.cluster import (
    ClusterCoordinator,
    QuotaExceeded,
    TenantAdmission,
    bind_tenant,
    current_tenant,
)
from learningorchestra_tpu.jobs.engine import (
    JobDeadlineExceeded,
    JobEngine,
    JobState,
    Preempted,
    current_attempt,
)
from learningorchestra_tpu.jobs.journal import (
    JobJournal,
    StaleEpochError,
)

__all__ = [
    "CancelToken",
    "ClusterCoordinator",
    "JobDeadlineExceeded",
    "JobEngine",
    "JobJournal",
    "JobState",
    "Preempted",
    "QuotaExceeded",
    "StaleEpochError",
    "TenantAdmission",
    "bind_tenant",
    "cancel_requested",
    "current_attempt",
    "current_tenant",
]
