"""Cooperative cancellation tokens for job bodies.

Python threads cannot be killed: the engine's deadline watchdog can
fail an overdue job and reclaim its worker slot and chip leases, but
the BODY keeps running as a zombie until it finishes on its own.  The
token closes that gap cooperatively — the engine binds one per
dispatched job (a contextvar, so it is readable anywhere down the job
body's call stack without threading a parameter through every layer),
flips it when the watchdog expires the job or a bounded shutdown drain
runs out of budget, and long-running bodies poll it between units of
work (the fit surfaces check it at every epoch boundary and wind down
exactly like an early stop).

The static rule ``loop-no-cancel-check`` (analysis/cancellation.py,
error severity) enforces the other half of the contract: a
long-running loop in the job-execution or serving planes that never
consults a cancel/stop/deadline signal fails the build.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading


class CancelToken:
    """One job's cancellation flag: set-once, thread-safe, poll-cheap.

    ``cancel()`` is idempotent and keeps the FIRST reason (the earliest
    cause — a watchdog deadline — is the one worth reporting, not the
    shutdown sweep that followed it)."""

    __slots__ = ("_event", "_reason")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._reason = ""

    def cancel(self, reason: str = "") -> None:
        if reason and not self._reason:
            self._reason = reason
        self._event.set()

    def cancelled(self) -> bool:
        return self._event.is_set()

    @property
    def reason(self) -> str:
        return self._reason

    def wait(self, timeout: float | None = None) -> bool:
        """Block until cancelled (or ``timeout``); → cancelled state.
        Lets a body sleep interruptibly instead of ``time.sleep``."""
        return self._event.wait(timeout)


#: The calling job body's token (None outside a dispatched job).
_TOKEN: contextvars.ContextVar = contextvars.ContextVar(
    "lo_cancel_token", default=None
)


def current_cancel_token() -> CancelToken | None:
    """The token bound around the current job dispatch, or None when
    not running under the engine (direct library use, tests)."""
    return _TOKEN.get()


def cancel_requested() -> bool:
    """True when the engine asked the current job body to wind down
    (watchdog deadline expiry or a bounded shutdown drain).  One
    contextvar read + one Event check — cheap enough per epoch/batch."""
    token = _TOKEN.get()
    return token is not None and token.cancelled()


@contextlib.contextmanager
def bind(token: CancelToken | None):
    """Bind ``token`` as the current job body's cancel token (the
    engine wraps each dispatch; tests wrap bodies directly)."""
    handle = _TOKEN.set(token)
    try:
        yield token
    finally:
        _TOKEN.reset(handle)
