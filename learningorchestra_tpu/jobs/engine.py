"""The async job engine.

Every reference service runs its pipeline body on a bare thread pool and
signals completion by flipping the ``finished`` boolean in the metadata doc,
recording exceptions as execution documents (reference:
binary_executor_image/binary_execution.py:155-186,
code_executor_image/code_execution.py:149-196 which also captures stdout).

This engine keeps that durable contract but adds what the reference lacks
(SURVEY §5.3):
- explicit job states (pending → running → finished | failed | cancelled)
  persisted in the metadata doc as ``jobState``;
- a process-local registry of live jobs so status/wait/cancel work without
  polling the store;
- structured retry for preemptible hardware: a job function may raise
  ``Preempted`` to request re-execution (TPU preemption is a first-class
  event, not a crash);
- weighted-fair scheduling across job CLASSES (classes = service types),
  the reference's Spark FAIR scheduler pools (reference:
  builder_image/fairscheduler.xml:1-7, projection_image/server.py:51-69
  assign each service a pool so one service's burst can't monopolise
  executors).  Submissions enqueue per class; freed workers are handed
  to classes by weighted round-robin, so a ``function`` flood cannot
  queue-starve a training submission.
"""

from __future__ import annotations

import contextlib
import contextvars
import io
import random
import threading
import time
import traceback
from collections import OrderedDict, deque
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable

from learningorchestra_tpu import faults
from learningorchestra_tpu.concurrency_rt import make_lock
from learningorchestra_tpu.jobs import cancel as jobs_cancel
from learningorchestra_tpu.jobs import journal as jobs_journal
from learningorchestra_tpu.jobs.cancel import CancelToken
from learningorchestra_tpu.log import capture_thread_stdout, get_logger, kv
from learningorchestra_tpu.obs import tracing
from learningorchestra_tpu.store import ArtifactStore

logger = get_logger("jobs")

#: Which retry attempt the calling job body is running as: 0 on the
#: first execution, N after N preemptions.  Job bodies read it through
#: :func:`current_attempt` to adapt — the executor service resumes a
#: retried train fit from its newest managed checkpoint instead of
#: epoch 0 (services/executor.py), without the engine knowing anything
#: about checkpoints.
_ATTEMPT: contextvars.ContextVar = contextvars.ContextVar(
    "lo_job_attempt", default=0
)


def current_attempt() -> int:
    """0 on a job's first execution, N inside its Nth preemption
    retry.  Valid anywhere down the job body's call stack (the engine
    binds it around each attempt)."""
    return _ATTEMPT.get()


def _flight():
    """Lazy flight-recorder handle (obs/flight.py): dispatch, retry,
    fence and terminal decisions land in the ``jobs`` ring."""
    from learningorchestra_tpu.obs import flight

    return flight


def _current_tenant():
    """The requesting tenant bound by the API tier, or None (lazy
    import keeps jobs.cluster out of the raw-engine import path)."""
    from learningorchestra_tpu.jobs.cluster import current_tenant

    return current_tenant()


def _bundle():
    """Lazy debug-bundle handle (obs/bundle.py): retries-exhausted and
    deadline terminals ask for an incident bundle (no-op unless a
    server wired the singleton)."""
    from learningorchestra_tpu.obs import bundle

    return bundle


def _job_metrics():
    """Engine instrumentation handles, resolved per use so a registry
    reset (tests, the bench's on/off probe) takes effect immediately."""
    from learningorchestra_tpu.obs.metrics import get_registry

    reg = get_registry()
    return (
        reg.histogram(
            "lo_jobs_queue_wait_seconds",
            "Queue wait from submit to dispatch, per fairness class.",
            labels=("job_class",),
            buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0,
                     60.0, 300.0, 1800.0),
        ),
        reg.counter(
            "lo_jobs_total",
            "Job state transitions by class (finished/failed are "
            "terminal; preempted counts each retry attempt).",
            labels=("job_class", "state"),
        ),
    )


class JobState:
    PENDING = "pending"
    RUNNING = "running"
    FINISHED = "finished"
    FAILED = "failed"
    CANCELLED = "cancelled"


class Preempted(Exception):
    """Raised by a job body to request re-execution after preemption."""


class JobDeadlineExceeded(Exception):
    """A job body ran past its deadline; the watchdog failed the job
    and reclaimed its worker and leases (the body itself cannot be
    killed — it finishes as an abandoned zombie whose result is
    discarded, the same semantics as a gateway-timed-out handler)."""


class JobEngine:
    #: Watchdog poll cadence.  Deadlines are a coarse hang bound, not a
    #: scheduler — sub-100ms precision is not a goal.
    WATCHDOG_INTERVAL_S = 0.1

    def __init__(
        self,
        artifacts: ArtifactStore,
        max_workers: int = 8,
        max_preemption_retries: int = 3,
        class_weights: dict[str, int] | None = None,
        retry_backoff_s: float = 0.05,
        retry_backoff_max_s: float = 5.0,
        deadline_s: float = 0.0,
        shutdown_drain_s: float = 0.0,
    ):
        self.artifacts = artifacts
        self.max_workers = max_workers
        # One dedicated thread per DISPATCHED job, gated by _inflight
        # (< max_workers), not a ThreadPoolExecutor: a fixed pool's
        # own thread cap would silently double the concurrency gate —
        # when the deadline watchdog reclaims a hung job's worker
        # slot, the zombie body still pins its thread, and an
        # equal-sized pool would have no thread left for the very job
        # the reclaim freed a slot for.  Threads are trivial next to
        # job bodies (model fits, dataset loads).
        self._threads: set[threading.Thread] = set()
        self.max_preemption_retries = max_preemption_retries
        # Preemption-retry backoff: attempt N sleeps
        # min(max, base * 2**(N-1)) * jitter, jitter ~ U[0.5, 1.5).
        # Immediate zero-backoff retries would slam a preempting
        # device pool in lockstep with every other retrying job —
        # the thundering-herd the jitter decorrelates.
        self.retry_backoff_s = max(0.0, float(retry_backoff_s))
        self.retry_backoff_max_s = max(0.0, float(retry_backoff_max_s))
        # Default wall-clock bound per dispatched job run (preemption
        # retries included); <= 0 disables.  Per-submit deadline_s
        # overrides.
        self.default_deadline_s = float(deadline_s)
        # Graceful-shutdown drain budget: shutdown(wait=True) waits at
        # most this long for running/queued work, then flips every
        # outstanding body's cancel token and joins with a short grace
        # before abandoning stragglers.  <= 0 keeps the legacy
        # unbounded drain (cooperating bodies still exit early when
        # the watchdog cancels them).  Env: LO_TPU_JOB_DRAIN_S.
        self.shutdown_drain_s = float(shutdown_drain_s)
        # Chip-lease pool (set by the service context): the deadline
        # watchdog revokes an expired job's leases through it so the
        # zombie body cannot pin chips it no longer owns.
        self.leaser = None
        # name -> dispatch record for RUNNING jobs ({t0, deadline,
        # future, job_class, ctl}); the watchdog scans it.
        self._running_recs: dict[str, dict] = {}
        self._watchdog: threading.Thread | None = None
        self._watchdog_wake = threading.Event()
        self._futures: dict[str, Future] = {}
        self._last_tracebacks: dict[str, str] = {}
        self._lock = make_lock("JobEngine._lock")
        # Weighted-fair dispatch state: per-class FIFO queues served by
        # weighted round-robin as workers free up.  A class's weight is
        # how many consecutive dispatches it gets per turn (default 1 —
        # equal shares, the reference fairscheduler's FAIR default).
        self.class_weights = dict(class_weights or {})
        self._queues: dict[str, deque] = {}
        self._rr_order: list[str] = []
        self._rr_idx = 0
        self._credits: dict[str, int] = {}
        self._inflight = 0
        self._shutdown = False
        # Warm-start hints (train/compile_cache.py): services tag a
        # submission with a program key and report it warm once the
        # job's compiled programs are cached; within a class's WRR
        # turn the dispatcher prefers queued jobs whose programs are
        # already compiled, so a freed worker starts stepping instead
        # of tracing.  Bounded FIFO — a hint registry, not a ledger.
        self._warm_keys: "OrderedDict[str, None]" = OrderedDict()
        self._max_warm_keys = 512
        # Starvation bound: after this many CONSECUTIVE warm bypasses
        # of a class's FIFO head, the head dispatches regardless — a
        # sustained stream of warm submissions cannot pin a cold job
        # in the queue forever.
        self._warm_bypass: dict[str, int] = {}
        self._max_warm_bypass = 4
        # Optional push-notification sink (services/webhooks.py): set
        # by the service context; completion paths call _notify.
        self.notifier = None
        # Crash-durable job journal (jobs/journal.py): set by the
        # service context.  Every state transition is recorded ahead
        # of its in-memory commit (group-committed through the
        # store's WAL by the journal flusher), and terminal commits
        # are fenced against the store's current engine epoch.  None
        # (raw engines, tests) disables both.
        self.journal = None
        # Cluster coordinator (jobs/cluster.py): set by the service
        # context when multi-engine dispatch is on.  Every dispatch
        # must CLAIM its job in the store-backed claim table before
        # running (a lost claim means a peer engine owns it — the
        # body never starts here).  None keeps the single-engine hot
        # path at one attribute check.
        self.cluster = None
        # Per-tenant admission counters (jobs/cluster.py
        # TenantAdmission): set by the service context when tenant
        # quotas are configured; the engine maintains queued/running
        # counts at submit/dispatch/terminal.  None disables.
        self.admission = None
        # Nested tenant fairness state: per-class last-served tenant
        # for the round-robin inside _pop_queued_locked.  The scan is
        # gated on _tenant_seen so untenanted deployments keep the
        # byte-identical popleft path.
        self._tenant_rr: dict[str, str] = {}
        self._tenant_seen = False

    def _journal(self, name: str, event: str, **fields) -> None:
        """Append one transition record; never raises (a journaling
        failure is counted and logged inside the journal — it must
        not take down the engine)."""
        if self.journal is not None:
            self.journal.append(event, name, **fields)

    def _fence_refused(self, name: str, req: dict) -> bool:
        """True when the calling body's engine epoch is stale: a newer
        recovery owns the store's metadata now — every terminal write
        below the check must be skipped (no lost-updates, no
        double-published state)."""
        if self.journal is None:
            return False
        try:
            self.journal.fence_check()
        except jobs_journal.StaleEpochError as exc:
            logger.error(kv(job=name, state="fenced",
                            error=str(exc), **req))
            _flight().record(
                "jobs", "fence_refused", job=name, error=str(exc),
            )
            return True
        return False

    def _notify(self, name: str, event: str) -> None:
        """Fire artifact state-change webhooks; never raises, never
        blocks (delivery is a daemon thread inside the notifier)."""
        if self.notifier is None:
            return
        try:
            meta = self.artifacts.metadata.read(name) or {}
            self.notifier.notify(name, event, meta)
        except Exception:  # noqa: BLE001 — jobs must finish regardless
            pass

    # -- submission -----------------------------------------------------------

    def submit(
        self,
        name: str,
        fn: Callable[[], Any],
        *,
        description: str | None = None,
        method: str | None = None,
        parameters: Any = None,
        capture_stdout: bool = False,
        on_success: Callable[[Any], dict | None] | None = None,
        job_class: str = "default",
        warm_key: str | None = None,
        deadline_s: float | None = None,
    ) -> Future:
        """Run ``fn`` asynchronously as the job for artifact ``name``.

        The artifact's metadata document must already exist (services create
        it before submitting, exactly as the reference creates metadata then
        spawns the thread — the HTTP response returns before the work runs).

        ``on_success(result)`` may return extra metadata fields to merge into
        the finished metadata doc (e.g. result row counts, checkpoint paths).

        ``job_class`` is the fairness pool (services pass their service
        type): queued work is dispatched to freed workers by weighted
        round-robin across classes, not global FIFO.

        ``warm_key``, when given, is the job's compiled-program tag:
        once any job reports it warm (:meth:`note_warm`, fed from
        train/compile_cache.py), queued jobs carrying the same tag are
        preferred WITHIN their class's round-robin turn — cross-class
        fairness is untouched; the hint only reorders one class's
        queue so freed workers favor zero-trace starts.

        ``deadline_s`` bounds the job body's wall clock per dispatch
        (None inherits the engine default, ``<= 0`` disables): past
        it, the watchdog marks the job failed, reclaims its worker
        slot and chip leases, and resolves the future with
        :class:`JobDeadlineExceeded`; the unkillable body finishes as
        an abandoned zombie whose writes are discarded.
        """
        # Observability: the submitting request's id (minted/echoed at
        # the API layer) rides into the job's metadata, log lines and
        # trace; the trace collects queue-wait/lease/compile/epoch
        # spans and persists into the execution ledger on completion.
        request_id = tracing.get_request_id()
        trace = tracing.new_trace(name, request_id)
        t_submit = time.monotonic()
        # The requesting tenant (bound from the X-Tenant header at the
        # API tier) rides into the queue entry for nested fair-share
        # dispatch and into the metadata for attribution.
        tenant = _current_tenant()
        # Persist the request parameters NOW, not only in the terminal
        # ledger record: a job killed mid-run (process death, store
        # failover) otherwise leaves no parameters anywhere, and the
        # recovery story — "bare PATCH re-uses the last recorded
        # parameters" — would be unfulfillable for a first run.
        stamp = {}
        if parameters is not None:
            stamp["requestParameters"] = parameters
        if request_id:
            stamp["requestId"] = request_id
        if tenant:
            stamp["tenant"] = tenant
        if stamp:
            try:
                self.artifacts.metadata.update(name, stamp)
            except Exception:  # noqa: BLE001 — recording is best-effort
                pass

        # Deadline control block, shared with the watchdog: once it
        # flips ``expired`` the (unkillable) body becomes a zombie —
        # every terminal write below checks it and discards instead of
        # overwriting the watchdog's recorded failure.
        ctl = {"expired": False}
        # Cooperative-cancellation token, bound around the dispatch so
        # the body can poll jobs_cancel.cancel_requested() anywhere
        # down its stack.  The watchdog flips it on deadline expiry
        # (zombies exit early instead of running to completion
        # discarded) and the bounded shutdown drain flips it when its
        # budget runs out.
        token = CancelToken()

        def run() -> Any:
            # Epoch stamp: the body carries the engine epoch of ITS
            # dispatch; terminal commits and artifact publications
            # compare it against the store's durable epoch (fencing).
            epoch = (
                self.journal.epoch if self.journal is not None
                else None
            )
            # Cluster claim: in the multi-engine world a dispatch may
            # only execute after winning the store-backed claim CAS —
            # a lost claim means a peer engine owns this job (its own
            # dispatch or a steal) and this future resolves None.  Any
            # claim-path error (chaos, store wobble) is treated as
            # LOST, never as a crash: the peer's copy still runs.
            claim_ctx = contextlib.nullcontext()
            if self.cluster is not None:
                try:
                    owned = self.cluster.claim(
                        name, info.get("enqueued_at")
                    )
                except Exception:  # noqa: BLE001
                    owned = False
                if not owned:
                    if self.admission is not None:
                        self.admission.note_dequeued(tenant)
                    _flight().record(
                        "jobs", "claim_lost", job=name,
                        jobClass=job_class,
                    )
                    logger.info(kv(job=name, state="claim_lost"))
                    return None
                from learningorchestra_tpu.jobs.cluster import bind_claim

                claim_ctx = bind_claim(name)
            if self.admission is not None:
                self.admission.note_dispatch(tenant, job_class)
            try:
                with jobs_cancel.bind(token), \
                        jobs_journal.stamp(epoch), claim_ctx:
                    return _run_attempts()
            finally:
                if self.admission is not None:
                    self.admission.note_done(tenant, job_class)
                if self.cluster is not None:
                    try:
                        self.cluster.release(name)
                    except Exception:  # noqa: BLE001 — release is
                        pass  # best-effort; the lease TTL reclaims

        def _run_attempts() -> Any:
            meta = self.artifacts.metadata
            ledger = self.artifacts.ledger
            attempts = 0
            t_start = time.monotonic()
            queue_wait_hist, jobs_total = _job_metrics()
            queue_wait_hist.observe(
                t_start - t_submit, job_class=job_class
            )
            if trace is not None:
                trace.add_span(
                    "queue_wait", t_submit, t_start,
                    attrs={"class": job_class},
                )
            job_sid = None  # the CURRENT attempt's span

            def trace_doc():
                """Finalize + snapshot the trace for a TERMINAL ledger
                record (None when tracing is off).  Ends the attempt
                span first, so the recorded durations cover exactly
                what ran."""
                if trace is None:
                    return None
                if job_sid is not None:
                    # None before the first attempt span begins (a
                    # cancel landing at the loop top).
                    trace.end(job_sid)
                return trace.to_doc()

            # req=<id> on every engine log line for this job: the one
            # grep key tying logs, metadata and the span tree together.
            req = {"req": request_id} if request_id else {}

            def _commit_cancelled(detail: str | None = None):
                """Terminal bookkeeping for a RUNNING job cancelled
                via the REST surface: the body wound down
                cooperatively (or died doing so) — record CANCELLED,
                not finished/failed.  Fenced like every terminal
                commit: a stale-epoch straggler's cancel must not
                lost-update metadata a newer recovery owns."""
                if self._fence_refused(name, req):
                    return None
                reason = token.reason or "cancel requested"
                logger.warning(kv(job=name, state="cancelled",
                                  reason=reason, **req))
                self._journal(name, "cancelled", reason=reason)
                meta.update(name, {
                    "jobState": JobState.CANCELLED,
                    "finished": False,
                    "exception": f"cancelled: {reason}"
                    + (f" ({detail})" if detail else ""),
                })
                jobs_total.inc(
                    job_class=job_class, state="cancelled"
                )
                ledger.record(
                    name,
                    description=description,
                    method=method,
                    parameters=parameters,
                    state=JobState.CANCELLED,
                    exception=detail,
                    trace=trace_doc(),
                )
                self._notify(name, "cancelled")
                return None
            while True:
                if ctl["expired"]:
                    # The watchdog expired this job while it slept in
                    # retry backoff: its failure is already recorded
                    # and its worker/leases handed on.  Starting
                    # another attempt here would mark_running over the
                    # watchdog's failed state and re-contend for the
                    # just-revoked leases.
                    logger.warning(kv(job=name, state="abandoned",
                                      **req))
                    return None
                if token.cancelled():
                    if ctl.get("cancelled"):
                        # REST-cancelled while between attempts
                        # (retry backoff): same terminal contract as
                        # a mid-run cancel — CANCELLED, not failed.
                        return _commit_cancelled()
                    # Cancelled between attempts without a deadline
                    # expiry: the bounded shutdown drain.  Record the
                    # terminal state (no watchdog wrote one) and stop
                    # instead of starting an attempt the process
                    # won't outlive.
                    err = (
                        f"cancelled: "
                        f"{token.reason or 'engine shutdown'}"
                    )
                    logger.warning(kv(job=name, state="cancelled",
                                      **req))
                    self._journal(name, "cancelled",
                                  reason=token.reason or None)
                    try:
                        meta.mark_failed(name, err)
                    except Exception:  # noqa: BLE001
                        pass
                    return None
                # One span PER ATTEMPT (attrs attempt=1..N): retries
                # are separate intervals in the persisted trace, not
                # one opaque job span swallowing every re-execution.
                if trace is not None:
                    job_sid = trace.begin(
                        "job", attrs={"attempt": attempts + 1}
                    )
                with tracing.activate(trace, job_sid):
                    self._journal(name, "running",
                                  attempt=attempts + 1)
                    meta.mark_running(name)
                    logger.info(kv(job=name, state="running",
                                   method=method, attempt=attempts + 1,
                                   **req))
                    # Feed-only event (no webhook fires for "running" —
                    # registrations are finished/failed; the global event
                    # feed still records the transition).
                    self._notify(name, "running")
                    # Rebound by the capture context; the empty default
                    # keeps the except-path buf.getvalue() calls safe if
                    # capture setup itself ever raises.
                    buf = io.StringIO()
                    attempt_token = _ATTEMPT.set(attempts)
                    try:
                        faults.hit("engine.dispatch")
                        _flight().record(
                            "jobs", "dispatch",
                            job=name, method=method,
                            jobClass=job_class, attempt=attempts + 1,
                        )
                        if capture_stdout:
                            # Thread-scoped: redirect_stdout would capture
                            # every concurrent thread's prints, not this
                            # job's (log.capture_thread_stdout docstring).
                            with capture_thread_stdout() as buf:
                                result = fn()
                        else:
                            result = fn()
                    except Preempted:
                        if ctl["expired"]:
                            # The watchdog already failed this job and
                            # reclaimed its worker — no retry, no
                            # state writes.
                            logger.warning(kv(job=name,
                                              state="abandoned", **req))
                            return None
                        attempts += 1
                        exhausted = (
                            attempts > self.max_preemption_retries
                        )
                        logger.warning(
                            kv(job=name, state="preempted",
                               attempt=attempts, **req)
                        )
                        self._journal(name, "preempted",
                                      attempt=attempts)
                        _flight().record(
                            "jobs", "preempt_retry",
                            job=name, attempt=attempts,
                            exhausted=exhausted,
                        )
                        jobs_total.inc(
                            job_class=job_class, state="preempted"
                        )
                        ledger.record(
                            name,
                            description=description,
                            method=method,
                            parameters=parameters,
                            state="preempted",
                            stdout=buf.getvalue() if capture_stdout
                            else None,
                            # The exhausting attempt IS the terminal
                            # record (no failed-state record follows
                            # it): persist the trace here or the
                            # failed run's spans are lost.
                            trace=trace_doc() if exhausted else None,
                        )
                        if not exhausted:
                            # Preemption survivors observable from the
                            # ordinary GET/poll path.
                            try:
                                meta.update(
                                    name, {"preemptions": attempts}
                                )
                            except Exception:  # noqa: BLE001
                                pass
                            if trace is not None:
                                trace.end(job_sid)
                            self._backoff(name, attempts, trace, req)
                            continue
                        if self._fence_refused(name, req):
                            return None
                        self._journal(
                            name, "failed",
                            reason="preemption retries exhausted",
                        )
                        meta.mark_failed(
                            name, "Preempted (retries exhausted)"
                        )
                        jobs_total.inc(
                            job_class=job_class, state="failed"
                        )
                        # Retries exhausted IS the incident: freeze
                        # the flight rings into a debug bundle.
                        _bundle().trigger(
                            "job_retries_exhausted",
                            job=name, attempts=attempts,
                        )
                        self._notify(name, "failed")
                        return None
                    except BaseException as exc:  # never kill workers
                        err = repr(exc)
                        if ctl["expired"]:
                            logger.warning(
                                kv(job=name, state="abandoned",
                                   error=err, **req)
                            )
                            return None
                        if self._fence_refused(name, req):
                            # Stale-epoch straggler: the newer
                            # recovery owns this job's metadata — a
                            # late "failed" would lost-update it.
                            return None
                        if ctl.get("cancelled"):
                            # The body died winding down after a
                            # cooperative cancel: that is a CANCELLED
                            # job, not a failure of the work itself.
                            return _commit_cancelled(err)
                        logger.error(
                            kv(job=name, state="failed", error=err,
                               dt=f"{time.monotonic() - t_start:.2f}s",
                               **req)
                        )
                        self._journal(name, "failed", reason=err)
                        _flight().record(
                            "jobs", "failed",
                            job=name, error=err[:200],
                        )
                        meta.mark_failed(name, err)
                        jobs_total.inc(
                            job_class=job_class, state="failed"
                        )
                        ledger.record(
                            name,
                            description=description,
                            method=method,
                            parameters=parameters,
                            state=JobState.FAILED,
                            exception=err,
                            stdout=buf.getvalue() if capture_stdout
                            else None,
                            trace=trace_doc(),
                        )
                        # Keep the traceback reachable for debugging
                        # without crashing the pool thread.
                        self._last_tracebacks[name] = (
                            traceback.format_exc()
                        )
                        self._notify(name, "failed")
                        return None
                    finally:
                        _ATTEMPT.reset(attempt_token)

                    if ctl["expired"]:
                        # Finished after its deadline: the job is
                        # already failed and its worker/leases handed
                        # on — a late mark_finished would resurrect it.
                        logger.warning(
                            kv(job=name, state="abandoned",
                               dt=f"{time.monotonic() - t_start:.2f}s",
                               **req)
                        )
                        return None
                    if ctl.get("cancelled"):
                        # REST-cancelled mid-run: the body observed
                        # its token and wound down early — its partial
                        # result must not publish as "finished".
                        return _commit_cancelled()
                    if self._fence_refused(name, req):
                        # Stale-epoch straggler racing a newer
                        # recovery: its completion must not publish.
                        return None
                    extra = on_success(result) if on_success else None
                    logger.info(
                        kv(job=name, state="finished",
                           dt=f"{time.monotonic() - t_start:.2f}s",
                           **req)
                    )
                    if self.journal is not None:
                        # Epoch stamp on metadata finalization: which
                        # engine life committed this artifact —
                        # readable from the ordinary GET/poll path.
                        extra = {
                            **(extra or {}),
                            "engineEpoch": jobs_journal.current_stamp(),
                        }
                    self._journal(name, "finished")
                    meta.mark_finished(name, extra or None)
                    jobs_total.inc(
                        job_class=job_class, state="finished"
                    )
                    ledger.record(
                        name,
                        description=description,
                        method=method,
                        parameters=parameters,
                        state=JobState.FINISHED,
                        stdout=buf.getvalue() if capture_stdout
                        else None,
                        trace=trace_doc(),
                    )
                    self._notify(name, "finished")
                    return result

        future: Future = Future()
        deadline = (
            self.default_deadline_s if deadline_s is None
            else float(deadline_s)
        )
        info = {
            "name": name,
            "job_class": job_class,
            "deadline": deadline,
            "ctl": ctl,
            "token": token,
            "tenant": tenant,
            # Submit wall-time: the claim table's supersede rule
            # compares it against a released claim's completion time
            # to refuse re-running work a peer already finished.
            "enqueued_at": time.time(),
        }
        # Queued-quota accounting BEFORE the enqueue (the dispatcher
        # may pop the entry the instant the lock drops; decrementing
        # before incrementing would clamp at 0 and leak).
        if self.admission is not None:
            self.admission.note_queued(tenant)
        # Journal ahead of the in-memory enqueue (and outside the
        # engine lock — a late-shutdown append drains inline through
        # the store's collection lock, and nesting that under _lock
        # would add a cross-module edge the dispatcher's hot path
        # doesn't need).
        if self.journal is not None:
            self.journal.record_submit(
                name, job_class=job_class, method=method,
                description=description, parameters=parameters,
                deadline_s=deadline if deadline else None,
                request_id=request_id,
            )
        with self._lock:
            refused = self._shutdown
            if not refused:
                if tenant:
                    self._tenant_seen = True
                queue = self._queues.get(job_class)
                if queue is None:
                    queue = self._queues[job_class] = deque()
                    self._rr_order.append(job_class)
                    self._credits[job_class] = self._weight(job_class)
                queue.append((run, future, warm_key, info))
                self._futures[name] = future
                self._prune_locked()
                self._dispatch_locked()
        if refused:
            if self.admission is not None:
                self.admission.note_dequeued(tenant)
            # Same contract as handing the job to a shut-down
            # executor (the pre-fairness behavior) — but the journal
            # already holds this job's submitted/queued pair, so
            # append the terminal (outside the lock: store writes)
            # or recovery would resurrect a submission the caller
            # was told failed.
            self._journal(
                name, "cancelled",
                reason="engine shut down before enqueue",
            )
            raise RuntimeError(
                "cannot submit jobs after engine shutdown"
            )
        return future

    def _backoff(self, name: str, attempt: int, trace, req: dict) -> None:
        """Sleep the jittered exponential backoff before retry
        ``attempt`` and record it as a ``retry_backoff`` span."""
        base = self.retry_backoff_s
        if base <= 0:
            return
        delay = min(
            self.retry_backoff_max_s,
            base * (2 ** max(0, attempt - 1)),
        ) * (0.5 + random.random())
        logger.info(kv(job=name, state="backoff",
                       delay=f"{delay:.3f}s", attempt=attempt, **req))
        t0 = time.monotonic()
        # Interruptible: a bounded shutdown drain (or the deadline
        # watchdog) flipping the token mid-backoff wakes the sleep —
        # otherwise a fully cooperative job could outsleep the drain's
        # grace window and be abandoned.
        token = jobs_cancel.current_cancel_token()
        if token is not None:
            token.wait(delay)
        else:
            time.sleep(delay)
        if trace is not None:
            trace.add_span(
                "retry_backoff", t0, time.monotonic(),
                attrs={"attempt": attempt, "delayS": round(delay, 4)},
            )

    # -- weighted-fair dispatch ----------------------------------------------

    def _weight(self, job_class: str) -> int:
        return max(1, int(self.class_weights.get(job_class, 1)))

    def note_warm(self, warm_key: str | None) -> None:
        """Record that programs for ``warm_key`` are compiled and
        cached — future queued jobs with this tag dispatch first
        within their class.  Bounded FIFO; never raises."""
        if not warm_key:
            return
        with self._lock:
            self._warm_keys.pop(warm_key, None)
            self._warm_keys[warm_key] = None
            while len(self._warm_keys) > self._max_warm_keys:
                self._warm_keys.popitem(last=False)

    def clear_warm_keys(self) -> None:
        """Drop every warm hint — wired to the compile cache's
        device-set invalidation (services/context.py): once the cache
        cleared, 'warm' jobs would trace like any other, so the
        preference is pure queue distortion."""
        with self._lock:
            self._warm_keys.clear()

    def _pop_queued_locked(self, queue: deque, job_class: str):
        """Pop the next job from one class's queue: the first queued
        job whose ``warm_key`` is known-warm if any (its compiled
        programs are cached — it starts stepping, not tracing), else
        strict FIFO.  Cancelled entries are skipped, never charged.
        At most ``_max_warm_bypass`` consecutive dispatches may jump
        the FIFO head; then the head runs (cold jobs are delayed, not
        starved)."""
        if (
            self._warm_keys
            and self._warm_bypass.get(job_class, 0) < self._max_warm_bypass
        ):
            for i, (runner, future, wk, info) in enumerate(queue):
                if future.cancelled():
                    continue
                if wk is not None and wk in self._warm_keys:
                    if i > 0:
                        self._warm_bypass[job_class] = (
                            self._warm_bypass.get(job_class, 0) + 1
                        )
                    else:
                        self._warm_bypass[job_class] = 0
                    del queue[i]
                    return runner, future, info
        self._warm_bypass[job_class] = 0
        if self._tenant_seen:
            picked = self._tenant_pick_locked(queue, job_class)
            if picked is not None:
                return picked
        runner, future, _wk, info = queue.popleft()
        return runner, future, info

    def _tenant_pick_locked(self, queue: deque, job_class: str):
        """Nested tenant round-robin INSIDE one class's WRR turn:
        when the queue holds work from more than one tenant, serve
        tenants in sorted cyclic order (per-class last-served
        pointer), popping the chosen tenant's oldest entry — so one
        tenant's flood delays, never starves, another tenant's jobs.
        Returns None with a single (or no) tenant present, keeping
        the plain-FIFO path byte-identical."""
        tenants: list[str] = []
        for _r, f, _wk, info in queue:
            if f.cancelled():
                continue
            t = info.get("tenant") or ""
            if t not in tenants:
                tenants.append(t)
        if len(tenants) <= 1:
            return None
        order = sorted(tenants)
        last = self._tenant_rr.get(job_class, "")
        pick = next((t for t in order if t > last), order[0])
        self._tenant_rr[job_class] = pick
        for i, (runner, future, _wk, info) in enumerate(queue):
            if future.cancelled():
                continue
            if (info.get("tenant") or "") == pick:
                del queue[i]
                return runner, future, info
        return None

    def _dispatch_locked(self) -> None:
        """Hand freed workers to queued jobs, class by class (WRR)."""
        while self._inflight < self.max_workers:
            item = self._pick_locked()
            if item is None:
                return
            runner, future, info = item
            if not future.set_running_or_notify_cancel():
                continue  # cancelled while queued — skip, pick again
            self._inflight += 1
            rec = self._register_running_locked(info, future)
            self._spawn_worker_locked(runner, future, rec)

    def _spawn_worker_locked(self, runner, future: Future,
                             rec: dict) -> None:
        thread = threading.Thread(
            target=self._run_dispatched, args=(runner, future, rec),
            name=f"lo-job-{rec['name']}", daemon=True,
        )
        self._threads.add(thread)
        thread.start()

    def _register_running_locked(self, info: dict, future: Future) -> dict:
        """Running-job record the deadline watchdog scans; caller
        holds the lock and has already charged ``_inflight``."""
        rec = {
            "name": info["name"],
            "future": future,
            "deadline": info["deadline"],
            "job_class": info["job_class"],
            "ctl": info["ctl"],
            "token": info["token"],
            "t0": time.monotonic(),
            "released": False,
        }
        self._running_recs[info["name"]] = rec
        if rec["deadline"] and rec["deadline"] > 0:
            self._ensure_watchdog_locked()
        return rec

    def _pick_locked(self):
        """Next queued job under weighted round-robin.

        The pointer stays on a class while it has queued work AND
        remaining credits (its weight's worth of consecutive
        dispatches), then refills that class's credits and advances —
        so over any contention window each class with work receives
        dispatches proportional to its weight.
        """
        # Jobs cancelled while queued are discarded without charging
        # their class's credits — a burst of cancellations must not
        # burn the class's turn.  cancel() runs under the same lock,
        # so cancelled() is stable here.
        for queue in self._queues.values():
            while queue and queue[0][1].cancelled():
                queue.popleft()
        if not any(self._queues.values()):
            return None
        # Two full passes bound the scan: the first may only refill
        # exhausted credits, the second must then land on a nonempty
        # class with fresh credits.
        for _ in range(2 * len(self._rr_order)):
            cls = self._rr_order[self._rr_idx % len(self._rr_order)]
            queue = self._queues[cls]
            while queue and queue[0][1].cancelled():
                queue.popleft()
            if queue and self._credits.get(cls, 0) > 0:
                self._credits[cls] -= 1
                return self._pop_queued_locked(queue, cls)
            self._credits[cls] = self._weight(cls)
            self._rr_idx += 1
        return None

    def _run_dispatched(self, runner, future: Future, rec: dict) -> None:
        try:
            result = runner()
        except BaseException as exc:  # pragma: no cover — run() is
            # exception-safe by construction; never leak a worker.
            try:
                future.set_exception(exc)
            except InvalidStateError:
                pass  # deadline watchdog resolved the future first
        else:
            try:
                future.set_result(result)
            except InvalidStateError:
                pass
        finally:
            with self._lock:
                if self._running_recs.get(rec["name"]) is rec:
                    del self._running_recs[rec["name"]]
                if not rec["released"]:
                    # An expired job's worker was already released by
                    # the watchdog — the zombie's return must not
                    # double-credit the pool.
                    rec["released"] = True
                    self._inflight -= 1
                    self._dispatch_locked()
                self._threads.discard(threading.current_thread())

    # -- deadline watchdog ----------------------------------------------------

    def _ensure_watchdog_locked(self) -> None:
        """Start the watchdog lazily — engines that never see a
        deadline'd job never grow the thread."""
        if self._shutdown:
            return  # nothing to enforce; don't unclear the wake event
        if self._watchdog is None or not self._watchdog.is_alive():
            self._watchdog_wake.clear()
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name="lo-job-watchdog", daemon=True,
            )
            self._watchdog.start()

    def _watchdog_loop(self) -> None:
        while True:
            self._watchdog_wake.wait(self.WATCHDOG_INTERVAL_S)
            expired: list[tuple[str, dict]] = []
            with self._lock:
                if self._shutdown:
                    return
                now = time.monotonic()
                armed = 0
                for name, rec in list(self._running_recs.items()):
                    deadline = rec["deadline"]
                    if (
                        not deadline or deadline <= 0
                        or rec["released"]
                    ):
                        continue
                    if now - rec["t0"] > deadline:
                        # Reclaim the worker NOW: the hung body keeps
                        # its thread (unkillable), but stops counting
                        # against max_workers so queued work
                        # dispatches.  Flipping the cancel token asks
                        # the zombie to exit early (fit loops poll it
                        # per epoch) instead of running to completion
                        # discarded.
                        rec["released"] = True
                        rec["ctl"]["expired"] = True
                        rec["token"].cancel(
                            f"deadline {deadline:g}s exceeded"
                        )
                        del self._running_recs[name]
                        self._inflight -= 1
                        expired.append((name, rec))
                    else:
                        armed += 1
                if expired:
                    self._dispatch_locked()
                if not armed and not expired:
                    # Nothing left to watch: exit rather than poll a
                    # long-lived idle process forever.  Cleared under
                    # the lock so _ensure_watchdog_locked restarts a
                    # fresh thread for the next deadline'd dispatch.
                    self._watchdog = None
                    return
            for name, rec in expired:
                self._expire_job(name, rec)

    def _expire_job(self, name: str, rec: dict) -> None:
        """Terminal bookkeeping for a job the watchdog timed out —
        runs OUTSIDE the engine lock (store writes, webhooks)."""
        deadline = rec["deadline"]
        err = (
            f"job exceeded its {deadline:g}s deadline; the watchdog "
            "failed it and reclaimed its worker and chip leases (the "
            "body finishes as an abandoned zombie)"
        )
        logger.error(kv(job=name, state="deadline",
                        deadlineS=deadline))
        self._journal(name, "deadline", reason=err)
        _flight().record(
            "jobs", "deadline", job=name, deadlineS=deadline,
        )
        # A watchdog-expired job is a crash-grade incident: snapshot
        # the rings before the evidence ages out.
        _bundle().trigger(
            "job_deadline", job=name, deadlineS=deadline,
        )
        _, jobs_total = _job_metrics()
        jobs_total.inc(job_class=rec["job_class"], state="deadline")
        try:
            self.artifacts.metadata.mark_failed(name, err)
        except Exception:  # noqa: BLE001 — the watchdog must survive
            pass
        try:
            self.artifacts.ledger.record(
                name, state="deadline", exception=err,
            )
        except Exception:  # noqa: BLE001
            pass
        if self.leaser is not None:
            try:
                freed = self.leaser.revoke(name)
                if freed:
                    logger.warning(kv(job=name, event="lease_revoked",
                                      devices=freed))
            except Exception:  # noqa: BLE001
                pass
        try:
            rec["future"].set_exception(JobDeadlineExceeded(err))
        except InvalidStateError:
            pass
        self._notify(name, "failed")

    # Cap retained completed futures/tracebacks so a long-lived API process
    # doesn't accumulate every past job's result object.
    _MAX_DONE_RETAINED = 128

    def _prune_locked(self) -> None:
        done = [n for n, f in self._futures.items() if f.done()]
        excess = len(done) - self._MAX_DONE_RETAINED
        for name in done[:max(excess, 0)]:
            self._futures.pop(name, None)
            self._last_tracebacks.pop(name, None)

    # -- status / control -----------------------------------------------------

    def state(self, name: str) -> str:
        meta = self.artifacts.metadata.read(name)
        if meta is None:
            raise KeyError(name)
        return meta.get(
            "jobState",
            JobState.FINISHED if meta.get("finished") else JobState.PENDING,
        )

    def wait(self, name: str, timeout: float | None = None) -> Any:
        """Block until the job for ``name`` completes; returns its result.

        (Clients normally poll GET instead — this is for in-process callers
        and tests.)
        """
        with self._lock:
            future = self._futures.get(name)
        if future is None:
            return None
        return future.result(timeout=timeout)

    def cancel(self, name: str):
        """Cancel a queued or RUNNING job.

        Queued: the future is cancelled before dispatch → ``True``
        (the job never runs).  Running: the body's CancelToken is
        flipped → ``"running"`` — the fit surfaces poll it per
        epoch/batch and wind down like an early stop, after which the
        engine records a journaled ``cancelled`` terminal state
        instead of ``finished``.  ``False`` when the job is neither
        (already terminal, or unknown).
        """
        running_rec = None
        with self._lock:
            # future.cancel() under the engine lock: the dispatcher's
            # cancelled() checks in _pick_locked run under the same
            # lock, so a cancellation can never land between a queue
            # pop and its dispatch — the no-credit-burn guarantee
            # depends on this.
            future = self._futures.get(name)
            cancelled = future is not None and future.cancel()
            if cancelled:
                cancelled_class = "unknown"
                cancelled_tenant = None
                for cls, queue in self._queues.items():
                    for _r, f, _wk, qinfo in queue:
                        if f is future:
                            cancelled_class = cls
                            cancelled_tenant = qinfo.get("tenant")
                            break
            if not cancelled:
                rec = self._running_recs.get(name)
                if rec is not None and not rec["released"]:
                    # Cooperative cancel of the RUNNING body: flag the
                    # control block so the terminal commit records
                    # CANCELLED, then flip the token (the order means
                    # a body that observes the token always finds the
                    # flag set).
                    rec["ctl"]["cancelled"] = True
                    rec["token"].cancel("cancel requested")
                    running_rec = rec
        # Store writes outside the engine lock.
        if cancelled:
            if self.admission is not None:
                # The entry left the queue without dispatching — the
                # tenant's queued count must not leak.
                self.admission.note_dequeued(cancelled_tenant)
            self._journal(name, "cancelled",
                          reason="cancelled while queued")
            self.artifacts.metadata.update(
                name, {"jobState": JobState.CANCELLED, "finished": False}
            )
            # Same observability as the running-cancel commit: ledger
            # row, cancelled counter, webhook/event-feed notify — a
            # watcher of the queued job must see the terminal
            # transition, not wait forever.
            _, jobs_total = _job_metrics()
            jobs_total.inc(
                job_class=cancelled_class, state="cancelled"
            )
            try:
                self.artifacts.ledger.record(
                    name, state=JobState.CANCELLED,
                    exception="cancelled while queued",
                )
            except Exception:  # noqa: BLE001 — cancel must succeed
                pass
            self._notify(name, "cancelled")
            return True
        if running_rec is not None:
            self._journal(name, "cancel_requested")
            return "running"
        return False

    def running_jobs(self) -> list[str]:
        with self._lock:
            return [n for n, f in self._futures.items() if not f.done()]

    def queue_depths(self, include_empty: bool = False) -> dict[str, int]:
        """Queued-but-undispatched jobs per class (the fairness pools) —
        the ops status page's contention gauge.  ``include_empty``
        keeps drained classes at 0 (the Prometheus collector needs the
        series to REPORT zero, not vanish and go stale)."""
        with self._lock:
            return {
                cls: len(q)
                for cls, q in self._queues.items()
                if q or include_empty
            }

    def queue_depths_by_tenant(self) -> dict[tuple, int]:
        """Queued-but-undispatched jobs per ``(class, tenant)`` — the
        per-tenant labels the metrics endpoint adds to
        ``lo_jobs_queue_depth`` once any tenanted submission arrived
        (empty dict otherwise, so untenanted deployments emit no
        extra series)."""
        with self._lock:
            if not self._tenant_seen:
                return {}
            out: dict[tuple, int] = {}
            for cls, q in self._queues.items():
                for _r, f, _wk, info in q:
                    if f.cancelled():
                        continue
                    key = (cls, info.get("tenant") or "")
                    out[key] = out.get(key, 0) + 1
            return out

    #: Post-cancel join grace inside a bounded shutdown drain: once
    #: the drain budget lapses and every outstanding token is flipped,
    #: cooperating bodies get this long to wind down before being
    #: abandoned (they poll the token per epoch/batch, so the grace
    #: only needs to cover one unit of work).
    SHUTDOWN_GRACE_S = 2.0

    def shutdown(self, wait: bool = True,
                 drain_timeout_s: float | None = None,
                 grace_s: float | None = None) -> None:
        """Stop accepting work; with ``wait``, drain what was accepted.

        The drain is BOUNDED when ``drain_timeout_s`` (default: the
        engine's ``shutdown_drain_s``) is positive: past the budget,
        every outstanding job's cancel token is flipped — cooperating
        bodies (the fit surfaces poll per epoch) exit early as if
        early-stopped — still-queued futures are cancelled, and after
        ``grace_s`` any thread still running is abandoned (logged)
        rather than joined forever.  A deadline-expired zombie can
        therefore no longer hang a graceful shutdown.  ``<= 0`` keeps
        the legacy unbounded drain.
        """
        with self._lock:
            self._shutdown = True
            self._watchdog_wake.set()
            # Still-queued jobs keep dispatching as workers free (each
            # completion re-enters _dispatch_locked), capped at
            # max_workers throughout — shutdown(wait=True) must run
            # every accepted job, exactly the pre-fairness contract.
            # Without the kick, jobs queued behind idle workers would
            # be orphaned with their metadata stuck at "pending".
            # (Deadlines stop being enforced here — the watchdog is
            # exiting; the drain budget below bounds the wait instead.)
            self._dispatch_locked()
        if not wait:
            return
        budget = (
            self.shutdown_drain_s if drain_timeout_s is None
            else float(drain_timeout_s)
        )
        deadline = (
            time.monotonic() + budget if budget > 0 else None
        )
        while True:
            with self._lock:
                thread = next(iter(self._threads), None)
                drained = (
                    thread is None
                    and not any(self._queues.values())
                    and self._inflight == 0
                )
            if drained:
                return
            if deadline is not None and time.monotonic() >= deadline:
                break  # budget spent — cooperative-cancel phase
            if thread is None:
                # Transient gap between a worker freeing and the next
                # queued job's thread appearing.
                time.sleep(0.005)
                continue
            if deadline is None:
                thread.join()
            else:
                thread.join(
                    min(0.2, max(0.0, deadline - time.monotonic()))
                )
        # Drain budget exhausted: cancel everything outstanding —
        # running bodies via their tokens (zombies were already
        # cancelled by the watchdog at expiry), queued-never-
        # dispatched jobs via their futures so waiters unblock — then
        # give cooperating threads one grace window and abandon the
        # rest (they are daemon threads; their writes race nothing:
        # the store outlives them only within this process).
        with self._lock:
            stragglers = list(self._threads)
            for rec in self._running_recs.values():
                rec["token"].cancel("engine shutdown drain deadline")
            dropped: list[tuple] = []
            for queue in self._queues.values():
                for _runner, queued_future, _wk, qinfo in queue:
                    if queued_future.cancel():
                        dropped.append(
                            (qinfo["name"], qinfo.get("tenant"))
                        )
                queue.clear()
        # Same terminal metadata the explicit cancel() path writes —
        # without it the pre-created doc would sit at "pending"
        # forever (phantom jobs after restart).  Outside the lock:
        # store writes.
        for name, drop_tenant in dropped:
            if self.admission is not None:
                self.admission.note_dequeued(drop_tenant)
            self._journal(name, "cancelled",
                          reason="shutdown drain deadline")
            try:
                self.artifacts.metadata.update(
                    name,
                    {"jobState": JobState.CANCELLED,
                     "finished": False},
                )
            except Exception:  # noqa: BLE001 — shutdown must finish
                pass
        grace = (
            self.SHUTDOWN_GRACE_S if grace_s is None
            else float(grace_s)
        )
        grace_deadline = time.monotonic() + max(0.0, grace)
        for thread in stragglers:
            thread.join(
                max(0.0, grace_deadline - time.monotonic())
            )
        leftover = [t.name for t in stragglers if t.is_alive()]
        if dropped or leftover:
            logger.error(kv(
                event="shutdown_drain_bounded",
                budgetS=budget, droppedQueued=len(dropped),
                abandoned=len(leftover),
                threads=",".join(leftover[:8]),
            ))
