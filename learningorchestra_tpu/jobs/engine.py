"""The async job engine.

Every reference service runs its pipeline body on a bare thread pool and
signals completion by flipping the ``finished`` boolean in the metadata doc,
recording exceptions as execution documents (reference:
binary_executor_image/binary_execution.py:155-186,
code_executor_image/code_execution.py:149-196 which also captures stdout).

This engine keeps that durable contract but adds what the reference lacks
(SURVEY §5.3):
- explicit job states (pending → running → finished | failed | cancelled)
  persisted in the metadata doc as ``jobState``;
- a process-local registry of live jobs so status/wait/cancel work without
  polling the store;
- structured retry for preemptible hardware: a job function may raise
  ``Preempted`` to request re-execution (TPU preemption is a first-class
  event, not a crash);
- weighted-fair scheduling across job CLASSES (classes = service types),
  the reference's Spark FAIR scheduler pools (reference:
  builder_image/fairscheduler.xml:1-7, projection_image/server.py:51-69
  assign each service a pool so one service's burst can't monopolise
  executors).  Submissions enqueue per class; freed workers are handed
  to classes by weighted round-robin, so a ``function`` flood cannot
  queue-starve a training submission.
"""

from __future__ import annotations

import io
import threading
import time
import traceback
from collections import OrderedDict, deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable

from learningorchestra_tpu.log import capture_thread_stdout, get_logger, kv
from learningorchestra_tpu.obs import tracing
from learningorchestra_tpu.store import ArtifactStore

logger = get_logger("jobs")


def _job_metrics():
    """Engine instrumentation handles, resolved per use so a registry
    reset (tests, the bench's on/off probe) takes effect immediately."""
    from learningorchestra_tpu.obs.metrics import get_registry

    reg = get_registry()
    return (
        reg.histogram(
            "lo_jobs_queue_wait_seconds",
            "Queue wait from submit to dispatch, per fairness class.",
            labels=("job_class",),
            buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0,
                     60.0, 300.0, 1800.0),
        ),
        reg.counter(
            "lo_jobs_total",
            "Job state transitions by class (finished/failed are "
            "terminal; preempted counts each retry attempt).",
            labels=("job_class", "state"),
        ),
    )


class JobState:
    PENDING = "pending"
    RUNNING = "running"
    FINISHED = "finished"
    FAILED = "failed"
    CANCELLED = "cancelled"


class Preempted(Exception):
    """Raised by a job body to request re-execution after preemption."""


class JobEngine:
    def __init__(
        self,
        artifacts: ArtifactStore,
        max_workers: int = 8,
        max_preemption_retries: int = 3,
        class_weights: dict[str, int] | None = None,
    ):
        self.artifacts = artifacts
        self.max_workers = max_workers
        self.pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="lo-job"
        )
        self.max_preemption_retries = max_preemption_retries
        self._futures: dict[str, Future] = {}
        self._last_tracebacks: dict[str, str] = {}
        self._lock = threading.Lock()
        # Weighted-fair dispatch state: per-class FIFO queues served by
        # weighted round-robin as workers free up.  A class's weight is
        # how many consecutive dispatches it gets per turn (default 1 —
        # equal shares, the reference fairscheduler's FAIR default).
        self.class_weights = dict(class_weights or {})
        self._queues: dict[str, deque] = {}
        self._rr_order: list[str] = []
        self._rr_idx = 0
        self._credits: dict[str, int] = {}
        self._inflight = 0
        self._shutdown = False
        # Warm-start hints (train/compile_cache.py): services tag a
        # submission with a program key and report it warm once the
        # job's compiled programs are cached; within a class's WRR
        # turn the dispatcher prefers queued jobs whose programs are
        # already compiled, so a freed worker starts stepping instead
        # of tracing.  Bounded FIFO — a hint registry, not a ledger.
        self._warm_keys: "OrderedDict[str, None]" = OrderedDict()
        self._max_warm_keys = 512
        # Starvation bound: after this many CONSECUTIVE warm bypasses
        # of a class's FIFO head, the head dispatches regardless — a
        # sustained stream of warm submissions cannot pin a cold job
        # in the queue forever.
        self._warm_bypass: dict[str, int] = {}
        self._max_warm_bypass = 4
        # Optional push-notification sink (services/webhooks.py): set
        # by the service context; completion paths call _notify.
        self.notifier = None

    def _notify(self, name: str, event: str) -> None:
        """Fire artifact state-change webhooks; never raises, never
        blocks (delivery is a daemon thread inside the notifier)."""
        if self.notifier is None:
            return
        try:
            meta = self.artifacts.metadata.read(name) or {}
            self.notifier.notify(name, event, meta)
        except Exception:  # noqa: BLE001 — jobs must finish regardless
            pass

    # -- submission -----------------------------------------------------------

    def submit(
        self,
        name: str,
        fn: Callable[[], Any],
        *,
        description: str | None = None,
        method: str | None = None,
        parameters: Any = None,
        capture_stdout: bool = False,
        on_success: Callable[[Any], dict | None] | None = None,
        job_class: str = "default",
        warm_key: str | None = None,
    ) -> Future:
        """Run ``fn`` asynchronously as the job for artifact ``name``.

        The artifact's metadata document must already exist (services create
        it before submitting, exactly as the reference creates metadata then
        spawns the thread — the HTTP response returns before the work runs).

        ``on_success(result)`` may return extra metadata fields to merge into
        the finished metadata doc (e.g. result row counts, checkpoint paths).

        ``job_class`` is the fairness pool (services pass their service
        type): queued work is dispatched to freed workers by weighted
        round-robin across classes, not global FIFO.

        ``warm_key``, when given, is the job's compiled-program tag:
        once any job reports it warm (:meth:`note_warm`, fed from
        train/compile_cache.py), queued jobs carrying the same tag are
        preferred WITHIN their class's round-robin turn — cross-class
        fairness is untouched; the hint only reorders one class's
        queue so freed workers favor zero-trace starts.
        """
        # Observability: the submitting request's id (minted/echoed at
        # the API layer) rides into the job's metadata, log lines and
        # trace; the trace collects queue-wait/lease/compile/epoch
        # spans and persists into the execution ledger on completion.
        request_id = tracing.get_request_id()
        trace = tracing.new_trace(name, request_id)
        t_submit = time.monotonic()
        # Persist the request parameters NOW, not only in the terminal
        # ledger record: a job killed mid-run (process death, store
        # failover) otherwise leaves no parameters anywhere, and the
        # recovery story — "bare PATCH re-uses the last recorded
        # parameters" — would be unfulfillable for a first run.
        stamp = {}
        if parameters is not None:
            stamp["requestParameters"] = parameters
        if request_id:
            stamp["requestId"] = request_id
        if stamp:
            try:
                self.artifacts.metadata.update(name, stamp)
            except Exception:  # noqa: BLE001 — recording is best-effort
                pass

        def run() -> Any:
            meta = self.artifacts.metadata
            ledger = self.artifacts.ledger
            attempts = 0
            t_start = time.monotonic()
            queue_wait_hist, jobs_total = _job_metrics()
            queue_wait_hist.observe(
                t_start - t_submit, job_class=job_class
            )
            if trace is not None:
                trace.add_span(
                    "queue_wait", t_submit, t_start,
                    attrs={"class": job_class},
                )
            job_sid = trace.begin("job") if trace is not None else None

            def trace_doc():
                """Finalize + snapshot the trace for a TERMINAL ledger
                record (None when tracing is off).  Ends the job span
                first, so the recorded durations cover exactly what
                ran."""
                if trace is None:
                    return None
                trace.end(job_sid)
                return trace.to_doc()

            # req=<id> on every engine log line for this job: the one
            # grep key tying logs, metadata and the span tree together.
            req = {"req": request_id} if request_id else {}
            with tracing.activate(trace, job_sid):
                while True:
                    meta.mark_running(name)
                    logger.info(kv(job=name, state="running",
                                   method=method, **req))
                    # Feed-only event (no webhook fires for "running" —
                    # registrations are finished/failed; the global event
                    # feed still records the transition).
                    self._notify(name, "running")
                    # Rebound by the capture context; the empty default
                    # keeps the except-path buf.getvalue() calls safe if
                    # capture setup itself ever raises.
                    buf = io.StringIO()
                    try:
                        if capture_stdout:
                            # Thread-scoped: redirect_stdout would capture
                            # every concurrent thread's prints, not this
                            # job's (log.capture_thread_stdout docstring).
                            with capture_thread_stdout() as buf:
                                result = fn()
                        else:
                            result = fn()
                    except Preempted:
                        attempts += 1
                        exhausted = (
                            attempts > self.max_preemption_retries
                        )
                        logger.warning(
                            kv(job=name, state="preempted",
                               attempt=attempts, **req)
                        )
                        jobs_total.inc(
                            job_class=job_class, state="preempted"
                        )
                        ledger.record(
                            name,
                            description=description,
                            method=method,
                            parameters=parameters,
                            state="preempted",
                            stdout=buf.getvalue() if capture_stdout
                            else None,
                            # The exhausting attempt IS the terminal
                            # record (no failed-state record follows
                            # it): persist the trace here or the
                            # failed run's spans are lost.
                            trace=trace_doc() if exhausted else None,
                        )
                        if not exhausted:
                            continue
                        meta.mark_failed(
                            name, "Preempted (retries exhausted)"
                        )
                        jobs_total.inc(
                            job_class=job_class, state="failed"
                        )
                        self._notify(name, "failed")
                        return None
                    except BaseException as exc:  # never kill workers
                        err = repr(exc)
                        logger.error(
                            kv(job=name, state="failed", error=err,
                               dt=f"{time.monotonic() - t_start:.2f}s",
                               **req)
                        )
                        meta.mark_failed(name, err)
                        jobs_total.inc(
                            job_class=job_class, state="failed"
                        )
                        ledger.record(
                            name,
                            description=description,
                            method=method,
                            parameters=parameters,
                            state=JobState.FAILED,
                            exception=err,
                            stdout=buf.getvalue() if capture_stdout
                            else None,
                            trace=trace_doc(),
                        )
                        # Keep the traceback reachable for debugging
                        # without crashing the pool thread.
                        self._last_tracebacks[name] = (
                            traceback.format_exc()
                        )
                        self._notify(name, "failed")
                        return None

                    extra = on_success(result) if on_success else None
                    logger.info(
                        kv(job=name, state="finished",
                           dt=f"{time.monotonic() - t_start:.2f}s",
                           **req)
                    )
                    meta.mark_finished(name, extra or None)
                    jobs_total.inc(
                        job_class=job_class, state="finished"
                    )
                    ledger.record(
                        name,
                        description=description,
                        method=method,
                        parameters=parameters,
                        state=JobState.FINISHED,
                        stdout=buf.getvalue() if capture_stdout
                        else None,
                        trace=trace_doc(),
                    )
                    self._notify(name, "finished")
                    return result

        future: Future = Future()
        with self._lock:
            if self._shutdown:
                # Same contract as handing the job to a shut-down
                # executor (the pre-fairness behavior).
                raise RuntimeError(
                    "cannot submit jobs after engine shutdown"
                )
            queue = self._queues.get(job_class)
            if queue is None:
                queue = self._queues[job_class] = deque()
                self._rr_order.append(job_class)
                self._credits[job_class] = self._weight(job_class)
            queue.append((run, future, warm_key))
            self._futures[name] = future
            self._prune_locked()
            self._dispatch_locked()
        return future

    # -- weighted-fair dispatch ----------------------------------------------

    def _weight(self, job_class: str) -> int:
        return max(1, int(self.class_weights.get(job_class, 1)))

    def note_warm(self, warm_key: str | None) -> None:
        """Record that programs for ``warm_key`` are compiled and
        cached — future queued jobs with this tag dispatch first
        within their class.  Bounded FIFO; never raises."""
        if not warm_key:
            return
        with self._lock:
            self._warm_keys.pop(warm_key, None)
            self._warm_keys[warm_key] = None
            while len(self._warm_keys) > self._max_warm_keys:
                self._warm_keys.popitem(last=False)

    def clear_warm_keys(self) -> None:
        """Drop every warm hint — wired to the compile cache's
        device-set invalidation (services/context.py): once the cache
        cleared, 'warm' jobs would trace like any other, so the
        preference is pure queue distortion."""
        with self._lock:
            self._warm_keys.clear()

    def _pop_queued_locked(self, queue: deque, job_class: str):
        """Pop the next job from one class's queue: the first queued
        job whose ``warm_key`` is known-warm if any (its compiled
        programs are cached — it starts stepping, not tracing), else
        strict FIFO.  Cancelled entries are skipped, never charged.
        At most ``_max_warm_bypass`` consecutive dispatches may jump
        the FIFO head; then the head runs (cold jobs are delayed, not
        starved)."""
        if (
            self._warm_keys
            and self._warm_bypass.get(job_class, 0) < self._max_warm_bypass
        ):
            for i, (runner, future, wk) in enumerate(queue):
                if future.cancelled():
                    continue
                if wk is not None and wk in self._warm_keys:
                    if i > 0:
                        self._warm_bypass[job_class] = (
                            self._warm_bypass.get(job_class, 0) + 1
                        )
                    else:
                        self._warm_bypass[job_class] = 0
                    del queue[i]
                    return runner, future
        self._warm_bypass[job_class] = 0
        runner, future, _wk = queue.popleft()
        return runner, future

    def _dispatch_locked(self) -> None:
        """Hand freed workers to queued jobs, class by class (WRR)."""
        while self._inflight < self.max_workers:
            item = self._pick_locked()
            if item is None:
                return
            runner, future = item
            if not future.set_running_or_notify_cancel():
                continue  # cancelled while queued — skip, pick again
            self._inflight += 1
            self.pool.submit(self._run_dispatched, runner, future)

    def _pick_locked(self):
        """Next queued job under weighted round-robin.

        The pointer stays on a class while it has queued work AND
        remaining credits (its weight's worth of consecutive
        dispatches), then refills that class's credits and advances —
        so over any contention window each class with work receives
        dispatches proportional to its weight.
        """
        # Jobs cancelled while queued are discarded without charging
        # their class's credits — a burst of cancellations must not
        # burn the class's turn.  cancel() runs under the same lock,
        # so cancelled() is stable here.
        for queue in self._queues.values():
            while queue and queue[0][1].cancelled():
                queue.popleft()
        if not any(self._queues.values()):
            return None
        # Two full passes bound the scan: the first may only refill
        # exhausted credits, the second must then land on a nonempty
        # class with fresh credits.
        for _ in range(2 * len(self._rr_order)):
            cls = self._rr_order[self._rr_idx % len(self._rr_order)]
            queue = self._queues[cls]
            while queue and queue[0][1].cancelled():
                queue.popleft()
            if queue and self._credits.get(cls, 0) > 0:
                self._credits[cls] -= 1
                return self._pop_queued_locked(queue, cls)
            self._credits[cls] = self._weight(cls)
            self._rr_idx += 1
        return None

    def _run_dispatched(self, runner, future: Future) -> None:
        try:
            result = runner()
        except BaseException as exc:  # pragma: no cover — run() is
            # exception-safe by construction; never leak a worker.
            future.set_exception(exc)
        else:
            future.set_result(result)
        finally:
            with self._lock:
                self._inflight -= 1
                self._dispatch_locked()

    # Cap retained completed futures/tracebacks so a long-lived API process
    # doesn't accumulate every past job's result object.
    _MAX_DONE_RETAINED = 128

    def _prune_locked(self) -> None:
        done = [n for n, f in self._futures.items() if f.done()]
        excess = len(done) - self._MAX_DONE_RETAINED
        for name in done[:max(excess, 0)]:
            self._futures.pop(name, None)
            self._last_tracebacks.pop(name, None)

    # -- status / control -----------------------------------------------------

    def state(self, name: str) -> str:
        meta = self.artifacts.metadata.read(name)
        if meta is None:
            raise KeyError(name)
        return meta.get(
            "jobState",
            JobState.FINISHED if meta.get("finished") else JobState.PENDING,
        )

    def wait(self, name: str, timeout: float | None = None) -> Any:
        """Block until the job for ``name`` completes; returns its result.

        (Clients normally poll GET instead — this is for in-process callers
        and tests.)
        """
        with self._lock:
            future = self._futures.get(name)
        if future is None:
            return None
        return future.result(timeout=timeout)

    def cancel(self, name: str) -> bool:
        """Cancel if not yet started (running jobs are not interruptible —
        same as the reference, where a running job dies only with its
        container; SURVEY §5.3)."""
        with self._lock:
            # future.cancel() under the engine lock: the dispatcher's
            # cancelled() checks in _pick_locked run under the same
            # lock, so a cancellation can never land between a queue
            # pop and its dispatch — the no-credit-burn guarantee
            # depends on this.
            future = self._futures.get(name)
            cancelled = future is not None and future.cancel()
        if cancelled:
            self.artifacts.metadata.update(
                name, {"jobState": JobState.CANCELLED, "finished": False}
            )
            return True
        return False

    def running_jobs(self) -> list[str]:
        with self._lock:
            return [n for n, f in self._futures.items() if not f.done()]

    def queue_depths(self, include_empty: bool = False) -> dict[str, int]:
        """Queued-but-undispatched jobs per class (the fairness pools) —
        the ops status page's contention gauge.  ``include_empty``
        keeps drained classes at 0 (the Prometheus collector needs the
        series to REPORT zero, not vanish and go stale)."""
        with self._lock:
            return {
                cls: len(q)
                for cls, q in self._queues.items()
                if q or include_empty
            }

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            self._shutdown = True
            # Flush every still-queued job into the executor in fair
            # order before shutting it down: the executor's worker
            # count still caps concurrency, and shutdown(wait=True)
            # must run every accepted job — exactly the pre-fairness
            # contract, where submit() handed jobs straight to the
            # pool.  Without this, jobs queued above max_workers would
            # be orphaned with their metadata stuck at "pending".
            while True:
                item = self._pick_locked()
                if item is None:
                    break
                runner, future = item
                if not future.set_running_or_notify_cancel():
                    continue
                self._inflight += 1
                self.pool.submit(self._run_dispatched, runner, future)
        self.pool.shutdown(wait=wait)
