"""Scale-out control plane: multi-engine dispatch over the shared store.

The reference scales its orchestration tier by replicating one-container
-per-service workers behind the gateway (PAPER.md §L2-L4); here the
whole control plane was ONE process — the queue, lease table and fleet
state all lived in ``JobEngine``'s memory, so a second API process could
neither share work nor survive the first's death.  This module moves
dispatch ownership into the replicated document store so N engine
processes over one store root accept, schedule and execute concurrently
and safely:

- **Claim table.**  ``_job_claims`` is an ordinary store collection
  (it rides the WAL, so it ships to the standby with everything else).
  Before executing a queued job, an engine must CLAIM it: insert a
  claim document carrying the engine id and its durable epoch, or CAS
  an expired one over via :meth:`DocumentStore.compare_and_update`.
  Two engines can race a claim; exactly one wins.
- **Leases + work stealing.**  Claims are heartbeat-renewed; a claim
  whose heartbeat is older than ``ttl_s`` belongs to a dead (or
  partitioned) engine and the sweep loop steals it in claim-id order —
  the pre-crash queue admission order — handing each stolen job to the
  context's checkpoint-resume redispatch path.
- **Epoch fencing.**  Every claim records the claimant's engine epoch.
  The PR-15 fence (jobs/journal.py) delegates here during a cluster
  dispatch: a terminal commit is allowed only while the committing
  engine still OWNS the claim under its stamped epoch, so a stale
  engine revived after its claim was stolen is refused at publication
  — no double-run becomes no lost-update.
- **Per-tenant fair-share admission.**  :class:`TenantAdmission`
  enforces queued/running quotas per ``X-Tenant`` with counters kept in
  the same store collection, so every engine rejects identically (429
  + Retry-After); the engine's dispatch loop adds a nested tenant
  round-robin inside each job-class pool so one tenant's flood cannot
  starve another's jobs.

Cross-process coherence: the store's in-memory maps are per-process, so
every claim-table access runs under an exclusive ``fcntl`` file lock on
``<store_root>/_cluster.lock`` and re-reads the collection from its WAL
first (:meth:`DocumentStore.refresh`).  That is also why clustering
requires the **python** store backend — the native backend has no
refresh primitive (services/context.py disables clustering loudly when
it is missing).  Claim/heartbeat wall-time comparisons assume the
engines' clocks agree to within ``ttl_s`` (same-host processes or
NTP-disciplined hosts); bench.py's ``_claim_probe`` banks the claim
path's cost against a minimal dispatch.

Fault points: ``cluster.claim`` (claim CAS), ``cluster.heartbeat``
(renew) and ``cluster.steal`` (expired-claim takeover) — seeded chaos
drivers in tests/test_faults.py, the partition drill in
tests/test_control_plane.py.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from pathlib import Path

from learningorchestra_tpu import faults
from learningorchestra_tpu.concurrency_rt import make_lock, make_rlock
from learningorchestra_tpu.log import get_logger, kv

logger = get_logger("cluster")

__all__ = [
    "CLAIM_COLLECTION",
    "ClusterCoordinator",
    "FIT_CLASSES",
    "QuotaExceeded",
    "TenantAdmission",
    "bind_claim",
    "bind_tenant",
    "current_claim",
    "current_tenant",
]

#: The claim table.  Underscore prefix keeps it out of the artifact
#: namespace (boot recovery skips it); riding the store means it ships
#: to the standby through the ordinary ``*.wal`` glob.
CLAIM_COLLECTION = "_job_claims"

#: Cross-process mutual exclusion for the claim table (file next to
#: the WALs so every engine over one store root sees the same lock).
LOCK_FILE = "_cluster.lock"

#: Job classes that count against the per-tenant RUNNING quota — the
#: accelerator-holding fits; cheap metadata jobs only count as queued.
FIT_CLASSES = frozenset({"executor", "distributed"})

#: Released claims are kept this many TTLs as supersede markers (a
#: dead engine's stale queue entry must still see that its job already
#: finished elsewhere), then swept.
_RELEASED_KEEP_TTLS = 10.0

#: Claim-table mutations between compactions — bounds WAL growth from
#: the heartbeat loop.  Safe under the cluster file lock: every
#: cross-process accessor refreshes before reading or writing.
_COMPACT_EVERY = 256


# -- contextvars: the dispatching claim + the requesting tenant -------------

_claim_var: contextvars.ContextVar = contextvars.ContextVar(
    "lo_cluster_claim", default=None
)
_tenant_var: contextvars.ContextVar = contextvars.ContextVar(
    "lo_tenant", default=None
)


def current_claim() -> str | None:
    """Job name of the claim held by the current engine dispatch, or
    None outside one — the journal fence keys its delegation on this."""
    return _claim_var.get()


def current_tenant() -> str | None:
    """Tenant bound to the current request/job, or None."""
    return _tenant_var.get()


@contextlib.contextmanager
def bind_claim(job: str):
    token = _claim_var.set(job)
    try:
        yield
    finally:
        _claim_var.reset(token)


@contextlib.contextmanager
def bind_tenant(tenant: str | None):
    token = _tenant_var.set(tenant or None)
    try:
        yield
    finally:
        _tenant_var.reset(token)


# -- metrics ---------------------------------------------------------------


#: (registry, counter) pair — re-resolved only when reset_registry()
#: swapped the registry (tests); a dispatch-path dict-get otherwise.
_claims_cache: tuple = (None, None)


def _claims_counter():
    """Registry counter, cached per registry identity: claim() rides
    every clustered dispatch, so the per-use name lookup matters."""
    global _claims_cache
    from learningorchestra_tpu.obs.metrics import get_registry

    reg = get_registry()
    cached_reg, counter = _claims_cache
    if cached_reg is not reg:
        counter = reg.counter(
            "lo_cluster_claims_total",
            "Claim-table operations by outcome.",
            labels=("outcome",),
        )
        _claims_cache = (reg, counter)
    return counter


def _rejections_counter():
    from learningorchestra_tpu.obs.metrics import get_registry

    return get_registry().counter(
        "lo_admission_rejections_total",
        "Per-tenant admission rejections by reason.",
        labels=("tenant", "reason"),
    )


def _flight(event: str, **fields) -> None:
    from learningorchestra_tpu.obs import flight as obs_flight

    obs_flight.record("cluster", event, **fields)


class ClusterCoordinator:
    """One engine's membership in the store-backed dispatch plane.

    Lifecycle: construct → (context wires ``epoch`` + callbacks) →
    :meth:`join` → claims flow through :meth:`claim`/:meth:`release`
    around every dispatch → :meth:`close`.  All claim-table access is
    serialized by a re-entrant in-process lock plus the cross-process
    file lock, with a WAL refresh folding peer appends on entry.
    """

    def __init__(self, documents, store_root, *, engine_id: str,
                 heartbeat_s: float = 1.0, ttl_s: float = 5.0,
                 sweep_s: float = 2.0):
        import os

        self.documents = documents
        self.root = Path(store_root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.engine_id = engine_id or f"engine-{os.getpid()}"
        self.heartbeat_s = float(heartbeat_s)
        self.ttl_s = float(ttl_s)
        self.sweep_s = float(sweep_s)
        #: The durable engine epoch (journal-minted); the context sets
        #: this after the journal boots, before join().
        self.epoch = 0
        #: ``on_steal(job, prev_engine)`` — fired (outside the lock)
        #: for each claim stolen by the sweep.
        self.on_steal = None
        #: ``on_engine_dead(engine_id, epoch)`` — fired when an engine
        #: document expires, so queued-but-unclaimed work of the dead
        #: engine can be re-dispatched.
        self.on_engine_dead = None
        #: job → claim-doc ``_id`` fast path: _ids are stable for a
        #: doc's lifetime and never reused, so a hit turns the claim
        #: lookup into one find_one instead of a collection scan (a
        #: miss — peer GC'd the doc — falls back to the scan).
        self._claim_ids: dict[str, int] = {}
        self._lock = make_rlock("ClusterCoordinator._lock")
        self._depth = 0
        self._refreshed: set = set()
        self._lock_fh = None
        self._mutations = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._closed = False

    # -- the guard ---------------------------------------------------------

    @contextlib.contextmanager
    def _guard(self, refresh: tuple = (CLAIM_COLLECTION,)):
        """Exclusive claim-table session: in-process re-entrant lock +
        cross-process flock, refreshing each named collection from its
        WAL once per flock hold (peer appends fold in before any read
        or write; our own mutations then land at the true tail)."""
        import fcntl

        with self._lock:
            if self._depth == 0:
                if self._lock_fh is None:
                    self._lock_fh = open(self.root / LOCK_FILE, "a+")
                fcntl.flock(self._lock_fh, fcntl.LOCK_EX)
                self._refreshed = set()
            for name in refresh:
                if name not in self._refreshed:
                    self.documents.refresh(name)
                    self._refreshed.add(name)
            self._depth += 1
            try:
                yield
            finally:
                self._depth -= 1
                if self._depth == 0 and self._lock_fh is not None:
                    fcntl.flock(self._lock_fh, fcntl.LOCK_UN)

    def journal_guard(self):
        """The same exclusive session, refreshing the JOURNAL instead:
        installed as ``journal.exclusive`` so cross-process journal
        appends/replays cannot allocate conflicting ``_id``s."""
        from learningorchestra_tpu.jobs.journal import JOURNAL_COLLECTION

        return self._guard(refresh=(JOURNAL_COLLECTION,))

    @staticmethod
    def _now() -> float:
        return time.time()

    def _docs_locked(self) -> list:
        """All claim-table documents; [] before the first write ever
        creates the collection."""
        if not self.documents.collection_exists(CLAIM_COLLECTION):
            return []
        return self.documents.find(CLAIM_COLLECTION)

    def _find_locked(self, kind: str, key: str, value: str):
        for doc in self._docs_locked():
            if doc.get("kind") == kind and doc.get(key) == value:
                return doc
        return None

    def _find_claim_locked(self, job: str):
        _id = self._claim_ids.get(job)
        if _id is not None:
            doc = self.documents.find_one(CLAIM_COLLECTION, _id)
            if (
                doc is not None
                and doc.get("kind") == "claim"
                and doc.get("job") == job
            ):
                return doc
            self._claim_ids.pop(job, None)
        doc = self._find_locked("claim", "job", job)
        if doc is not None:
            self._claim_ids[job] = doc["_id"]
        return doc

    def _note_mutation_locked(self) -> None:
        self._mutations += 1
        if self._mutations >= _COMPACT_EVERY:
            self._mutations = 0
            try:
                self.documents.compact(CLAIM_COLLECTION)
            except Exception:  # noqa: BLE001 — compaction is an
                pass           # optimization, never a claim failure

    # -- claims ------------------------------------------------------------

    def claim(self, job: str, enqueued_at: float | None = None) -> bool:
        """Claim ``job`` for this engine; True means we own it and may
        execute.  ``enqueued_at`` (submit wall-time) guards the
        released-slot supersede rule: a queue entry older than the
        claim's completion was already finished by a peer that adopted
        it — executing it again would be the double-run.
        """
        # Chaos probe: an injected error models a claim-table wobble
        # mid-CAS — the engine treats any claim failure as "lost"
        # (the peer owns it), never as a crash.
        faults.hit("cluster.claim")
        now = self._now()
        with self._guard():
            doc = self._find_claim_locked(job)
            if doc is None:
                self._claim_ids[job] = self.documents.insert_one(
                    CLAIM_COLLECTION, {
                        "kind": "claim", "job": job,
                        "engine": self.engine_id, "epoch": self.epoch,
                        "hbAt": now, "state": "live", "doneAt": None,
                    }
                )
                self._note_mutation_locked()
                outcome = "acquired"
            elif doc.get("state") == "released":
                if (
                    enqueued_at is not None
                    and (doc.get("doneAt") or 0) > enqueued_at
                ):
                    # Finished by a peer AFTER this entry was queued:
                    # the work this entry describes already ran to a
                    # terminal publication elsewhere.
                    outcome = "superseded"
                else:
                    ok = self.documents.compare_and_update(
                        CLAIM_COLLECTION, doc["_id"],
                        {"engine": doc.get("engine"),
                         "state": "released"},
                        {"engine": self.engine_id, "epoch": self.epoch,
                         "hbAt": now, "state": "live", "doneAt": None},
                    )
                    self._note_mutation_locked()
                    outcome = "acquired" if ok else "lost"
            elif doc.get("engine") == self.engine_id:
                # Re-dispatch of a job we already own (preemption
                # retry, recovered boot): renew and proceed.  Skip the
                # WAL append when the lease is already fresh — the
                # heartbeat daemon owns renewals, so the steady-state
                # dispatch path pays no write here.
                if (
                    doc.get("epoch") != self.epoch
                    or now - (doc.get("hbAt") or 0) > self.heartbeat_s
                ):
                    self.documents.update_one(
                        CLAIM_COLLECTION, doc["_id"],
                        {"epoch": self.epoch, "hbAt": now},
                    )
                    self._note_mutation_locked()
                outcome = "acquired"
            elif now - (doc.get("hbAt") or 0) > self.ttl_s:
                # Expired peer claim: dispatch-time takeover by CAS —
                # two engines racing here both saw the same stale
                # owner, only one lands.
                ok = self.documents.compare_and_update(
                    CLAIM_COLLECTION, doc["_id"],
                    {"engine": doc.get("engine"),
                     "hbAt": doc.get("hbAt")},
                    {"engine": self.engine_id, "epoch": self.epoch,
                     "hbAt": now, "state": "live", "doneAt": None},
                )
                self._note_mutation_locked()
                outcome = "acquired" if ok else "lost"
            else:
                outcome = "lost"
        acquired = outcome == "acquired"
        _claims_counter().inc(
            outcome="acquired" if acquired else "lost"
        )
        _flight(
            "claim", job=job, outcome=outcome,
            engine=self.engine_id, epoch=self.epoch,
        )
        if not acquired:
            logger.info(kv(
                event="claim_" + outcome, job=job,
                engine=self.engine_id,
            ))
        return acquired

    def release(self, job: str) -> None:
        """Mark our claim released (with completion time) — kept as a
        supersede marker instead of deleted, so a straggler engine's
        stale queue entry for the same submission refuses to re-run."""
        with self._guard():
            doc = self._find_claim_locked(job)
            if doc is None or doc.get("engine") != self.engine_id:
                return
            self.documents.update_one(CLAIM_COLLECTION, doc["_id"], {
                "state": "released", "doneAt": self._now(),
            })
            self._note_mutation_locked()
        _claims_counter().inc(outcome="released")
        _flight(
            "release", job=job, engine=self.engine_id,
            epoch=self.epoch,
        )

    def verify(self, job: str, epoch: int | None = None) -> bool:
        """Fence delegate: does this engine still OWN the live claim
        for ``job`` (under ``epoch``, when stamped)?  False after a
        steal — the stolen-from engine's terminal commit must be
        refused even though its process never died."""
        with self._guard():
            doc = self._find_claim_locked(job)
            return (
                doc is not None
                and doc.get("state") == "live"
                and doc.get("engine") == self.engine_id
                and (epoch is None or doc.get("epoch") == epoch)
            )

    def claimable(self, job: str) -> bool:
        """Boot-recovery gate: may this engine adopt ``job``?  False
        while a LIVE peer holds its claim (the job is not orphaned —
        it is running over there)."""
        with self._guard():
            doc = self._find_claim_locked(job)
            if doc is None or doc.get("engine") == self.engine_id:
                return True
            if doc.get("state") == "released":
                return True
            return self._now() - (doc.get("hbAt") or 0) > self.ttl_s

    # -- heartbeat + sweep -------------------------------------------------

    def heartbeat(self) -> int:
        """Renew this engine's membership document and every live
        claim it holds; returns the renewed-claim count."""
        faults.hit("cluster.heartbeat")
        now = self._now()
        renewed = 0
        with self._guard():
            mine = self._find_locked("engine", "engine", self.engine_id)
            if mine is None:
                self.documents.insert_one(CLAIM_COLLECTION, {
                    "kind": "engine", "engine": self.engine_id,
                    "epoch": self.epoch, "hbAt": now,
                })
            else:
                self.documents.update_one(
                    CLAIM_COLLECTION, mine["_id"],
                    {"epoch": self.epoch, "hbAt": now},
                )
            for doc in self._docs_locked():
                if (
                    doc.get("kind") == "claim"
                    and doc.get("engine") == self.engine_id
                    and doc.get("state") == "live"
                ):
                    self.documents.update_one(
                        CLAIM_COLLECTION, doc["_id"], {"hbAt": now}
                    )
                    renewed += 1
            self._note_mutation_locked()
        _claims_counter().inc(outcome="renewed")
        _flight(
            "renew", engine=self.engine_id, epoch=self.epoch,
            claims=renewed,
        )
        return renewed

    def sweep(self) -> list[tuple]:
        """Steal expired peer claims (claim-id order = pre-crash queue
        admission order) and expire dead engine documents; fires the
        ``on_steal``/``on_engine_dead`` callbacks outside the lock.
        Returns the stolen ``(job, prev_engine)`` pairs."""
        now = self._now()
        stolen: list[tuple] = []
        dead: list[tuple] = []
        with self._guard():
            docs = self._docs_locked()
            for doc in docs:
                if (
                    doc.get("kind") == "engine"
                    and doc.get("engine") != self.engine_id
                    and now - (doc.get("hbAt") or 0) > self.ttl_s
                ):
                    dead.append(
                        (doc.get("engine"), doc.get("epoch") or 0)
                    )
                    self.documents.delete_one(
                        CLAIM_COLLECTION, doc["_id"]
                    )
                    self._note_mutation_locked()
            for doc in sorted(docs, key=lambda d: d["_id"]):
                if doc.get("kind") != "claim":
                    continue
                if (
                    doc.get("state") == "released"
                    and now - (doc.get("doneAt") or now)
                    > _RELEASED_KEEP_TTLS * self.ttl_s
                ):
                    self.documents.delete_one(
                        CLAIM_COLLECTION, doc["_id"]
                    )
                    self._note_mutation_locked()
                    continue
                if (
                    doc.get("state") == "live"
                    and doc.get("engine") != self.engine_id
                    and now - (doc.get("hbAt") or 0) > self.ttl_s
                ):
                    # Chaos probe: an injected error here models the
                    # sweeper crashing mid-steal — the claim stays
                    # with the (dead) owner and the NEXT sweep
                    # finishes the takeover.
                    faults.hit("cluster.steal")
                    ok = self.documents.compare_and_update(
                        CLAIM_COLLECTION, doc["_id"],
                        {"engine": doc.get("engine"),
                         "hbAt": doc.get("hbAt")},
                        {"engine": self.engine_id,
                         "epoch": self.epoch, "hbAt": now},
                    )
                    self._note_mutation_locked()
                    if ok:
                        stolen.append(
                            (doc.get("job"), doc.get("engine"))
                        )
        for job, prev in stolen:
            _claims_counter().inc(outcome="stolen")
            _flight(
                "steal", job=job, prev=prev,
                engine=self.engine_id, epoch=self.epoch,
            )
            logger.warning(kv(
                event="claim_stolen", job=job, prev=prev,
                engine=self.engine_id,
            ))
            if self.on_steal is not None:
                try:
                    self.on_steal(job, prev)
                except Exception:  # noqa: BLE001 — one bad redispatch
                    logger.exception(   # must not kill the sweeper
                        "steal callback failed for job %r", job
                    )
        # Bounded walk over this sweep's dead-engine list; "epoch" is
        # the fencing epoch, not a training loop.
        # lo-check: disable=loop-no-cancel-check
        for dead_engine, dead_epoch in dead:
            _flight(
                "engine_dead", dead=dead_engine, deadEpoch=dead_epoch,
                engine=self.engine_id,
            )
            logger.warning(kv(
                event="engine_dead", dead=dead_engine,
                deadEpoch=dead_epoch,
            ))
            if self.on_engine_dead is not None:
                try:
                    self.on_engine_dead(dead_engine, dead_epoch)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "engine-dead callback failed for %r", engine
                    )
        return stolen

    # -- membership --------------------------------------------------------

    def join(self) -> None:
        """Publish this engine's membership and start the heartbeat +
        sweep daemons."""
        self.heartbeat()
        if self.heartbeat_s > 0:
            t = threading.Thread(
                target=self._loop,
                args=(self.heartbeat_s, self.heartbeat),
                name=f"cluster-heartbeat-{self.engine_id}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        if self.sweep_s > 0:
            t = threading.Thread(
                target=self._loop, args=(self.sweep_s, self.sweep),
                name=f"cluster-sweep-{self.engine_id}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        logger.info(kv(
            event="cluster_join", engine=self.engine_id,
            epoch=self.epoch, heartbeat_s=self.heartbeat_s,
            ttl_s=self.ttl_s,
        ))

    def _loop(self, interval: float, fn) -> None:
        while not self._stop.wait(interval):
            try:
                fn()
            except Exception:  # noqa: BLE001 — a failed tick (chaos,
                # transient IO) must not kill the loop; the next tick
                # retries against fresh state.
                logger.exception("cluster loop tick failed")

    def status(self) -> dict:
        """The /cluster/status body: engines + claims as the store
        sees them right now."""
        now = self._now()
        with self._guard():
            docs = self._docs_locked()
        engines = []
        claims = []
        for doc in docs:
            if doc.get("kind") == "engine":
                engines.append({
                    "engine": doc.get("engine"),
                    "epoch": doc.get("epoch"),
                    "ageS": round(now - (doc.get("hbAt") or now), 3),
                    "live": now - (doc.get("hbAt") or 0) <= self.ttl_s,
                })
            elif doc.get("kind") == "claim":
                claims.append({
                    "job": doc.get("job"),
                    "engine": doc.get("engine"),
                    "epoch": doc.get("epoch"),
                    "state": doc.get("state"),
                    "ageS": round(now - (doc.get("hbAt") or now), 3),
                })
        return {
            "engine": self.engine_id,
            "epoch": self.epoch,
            "ttlS": self.ttl_s,
            "heartbeatS": self.heartbeat_s,
            "engines": engines,
            "claims": claims,
        }

    def close(self) -> None:
        """Leave the cluster: stop the loops and retract this engine's
        membership document (peers need not wait out the TTL)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        try:
            with self._guard():
                mine = self._find_locked(
                    "engine", "engine", self.engine_id
                )
                if mine is not None:
                    self.documents.delete_one(
                        CLAIM_COLLECTION, mine["_id"]
                    )
        except Exception:  # noqa: BLE001 — closing must not raise
            pass
        with self._lock:
            if self._lock_fh is not None:
                self._lock_fh.close()
                self._lock_fh = None


# -- per-tenant fair-share admission ----------------------------------------


class QuotaExceeded(Exception):
    """Tenant over quota → HTTP 429 + Retry-After."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class TenantAdmission:
    """Per-tenant queued/running quotas, enforced identically on every
    engine.

    Under clustering the counters live as ``tenant`` documents in the
    claim collection (read/written under the coordinator's guard), so
    engine B sees the jobs tenant X queued through engine A.  Without a
    cluster they are a local dict under a lock.  The API tier calls
    :meth:`check` on job-creating routes BEFORE any metadata exists
    (a quota rejection must not leave an orphan artifact); the engine
    maintains the counters at submit/dispatch/terminal.  The check and
    the increment are not one atomic step — a burst racing the window
    can overshoot a quota by the in-flight request count, which load
    shedding tolerates by design.
    """

    def __init__(self, max_queued: int = 0, max_running: int = 0,
                 retry_after_s: float = 1.0, cluster=None):
        self.max_queued = int(max_queued)
        self.max_running = int(max_running)
        self.retry_after_s = float(retry_after_s)
        self.cluster = cluster
        self._lock = make_lock("TenantAdmission._lock")
        self._local: dict[str, dict] = {}

    def _counts(self, tenant: str) -> tuple[int, int]:
        if self.cluster is not None:
            with self.cluster._guard():
                doc = self.cluster._find_locked(
                    "tenant", "tenant", tenant
                )
            if doc is None:
                return 0, 0
            return int(doc.get("queued") or 0), int(
                doc.get("running") or 0
            )
        with self._lock:
            rec = self._local.get(tenant)
            if rec is None:
                return 0, 0
            return rec["queued"], rec["running"]

    def _bump(self, tenant: str, field: str, delta: int) -> None:
        if self.cluster is not None:
            docs = self.cluster.documents
            with self.cluster._guard():
                doc = self.cluster._find_locked(
                    "tenant", "tenant", tenant
                )
                if doc is None:
                    doc = {"kind": "tenant", "tenant": tenant,
                           "queued": 0, "running": 0}
                    doc["_id"] = docs.insert_one(
                        CLAIM_COLLECTION, doc
                    )
                value = max(0, int(doc.get(field) or 0) + delta)
                docs.update_one(
                    CLAIM_COLLECTION, doc["_id"], {field: value}
                )
                self.cluster._note_mutation_locked()
            return
        with self._lock:
            rec = self._local.setdefault(
                tenant, {"queued": 0, "running": 0}
            )
            rec[field] = max(0, rec[field] + delta)

    def check(self, tenant: str | None) -> None:
        """Admission gate: raise :class:`QuotaExceeded` when ``tenant``
        is over its queued or running quota."""
        t = tenant or ""
        queued, running = self._counts(t)
        reason = None
        if self.max_queued > 0 and queued >= self.max_queued:
            reason, n, cap = "queued_quota", queued, self.max_queued
        elif self.max_running > 0 and running >= self.max_running:
            reason, n, cap = "running_quota", running, self.max_running
        if reason is None:
            return
        _rejections_counter().inc(tenant=t or "-", reason=reason)
        _flight(
            "quota_reject", tenant=t or "-", reason=reason,
            n=n, cap=cap,
        )
        raise QuotaExceeded(
            f"tenant {t or '<default>'!r} over its {reason.split('_')[0]}"
            f" quota ({n}/{cap}); retry after backoff",
            retry_after_s=self.retry_after_s,
        )

    def note_queued(self, tenant: str | None) -> None:
        self._bump(tenant or "", "queued", +1)

    def note_dequeued(self, tenant: str | None) -> None:
        """A queued entry left the queue WITHOUT dispatching (cancel,
        shutdown drop) — the queued count must not leak."""
        self._bump(tenant or "", "queued", -1)

    def note_dispatch(self, tenant: str | None, job_class: str) -> None:
        self._bump(tenant or "", "queued", -1)
        if job_class in FIT_CLASSES:
            self._bump(tenant or "", "running", +1)

    def note_done(self, tenant: str | None, job_class: str) -> None:
        if job_class in FIT_CLASSES:
            self._bump(tenant or "", "running", -1)

    def snapshot(self) -> dict:
        """Per-tenant counter view (the /cluster/status body)."""
        out: dict[str, dict] = {}
        if self.cluster is not None:
            with self.cluster._guard():
                docs = self.cluster._docs_locked()
            for doc in docs:
                if doc.get("kind") == "tenant":
                    out[doc.get("tenant") or ""] = {
                        "queued": int(doc.get("queued") or 0),
                        "running": int(doc.get("running") or 0),
                    }
            return out
        with self._lock:
            return {t: dict(rec) for t, rec in self._local.items()}
